#!/bin/bash
# Probes the axon tunnel every 5 min; appends result to .tpu_attempts.log.
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 120 python -c "import jax; d=jax.devices()[0]; print(d.device_kind)" 2>/dev/null | tail -1)
  if [ -n "$out" ] && [ "$out" != "cpu" ]; then
    echo "$ts ALIVE $out" >> /root/repo/.tpu_attempts.log
  else
    echo "$ts dead (timeout/err)" >> /root/repo/.tpu_attempts.log
  fi
  sleep 300
done
