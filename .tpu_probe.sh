#!/bin/bash
# Probes the axon tunnel every 5 min; on first ALIVE, kicks off the full
# measurement session (hack/tpu_session.sh) exactly once.
cd /root/repo || exit 1
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 120 python -c "import jax; d=jax.devices()[0]; print(d.device_kind)" 2>/dev/null | tail -1)
  if [ -n "$out" ] && [ "$out" != "cpu" ]; then
    echo "$ts ALIVE $out" >> .tpu_attempts.log
    if [ ! -e bench-results/.session_done ]; then
      mkdir -p bench-results
      echo "$ts launching hack/tpu_session.sh" >> .tpu_attempts.log
      bash hack/tpu_session.sh bench-results >> bench-results/session.log 2>&1
      rc=$?
      echo "$(date -u +%FT%TZ) session script exited rc=$rc" >> .tpu_attempts.log
      # only a clean run retires the launcher: a tunnel flap mid-session
      # (rc!=0) must retry at the next ALIVE window
      [ "$rc" -eq 0 ] && touch bench-results/.session_done
    fi
  else
    echo "$ts dead (timeout/err)" >> .tpu_attempts.log
  fi
  sleep 300
done
