"""CI plumbing: path→workflow mapping + release workflows (L6).

The reference's Prow config maps changed repo paths to Argo test workflows
(prow_config.yaml:1-8 — each entry: a workflow component, a trigger class,
and `include`/`job_types`), and releases images through dedicated Argo
workflows (releasing/releaser/components/workflows.jsonnet; per-component
releaser apps; postsubmits push to gcr.io/kubeflow-images-public).

Here the same two pieces, native:
- ``load_ci_config`` / ``select_workflows``: consume ``ci_config.yaml`` at
  the repo root (one entry per workflow: name, trigger, include globs) and
  answer "which workflows must run for this changed-file list" — the
  prow_config contract.
- ``release_workflow``: build the image-release Workflow manifest our
  engine runs (build → test → push DAG), the releaser analog.
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass, field
from typing import Optional

from ..utils import yamlio
from .engine import WORKFLOW_API_VERSION, WORKFLOW_KIND

TRIGGERS = ("presubmit", "postsubmit", "periodic")


@dataclass
class CIEntry:
    """One prow_config.yaml workflow entry."""

    name: str
    workflow: str                       # workflow template / component name
    trigger: str = "presubmit"
    include: list = field(default_factory=lambda: ["**"])
    params: dict = field(default_factory=dict)

    def matches(self, path: str) -> bool:
        path = path.lstrip("./")
        for pattern in self.include:
            # '**' crosses directory boundaries (prow-style), fnmatch's
            # '*' does too — normalize so both spellings work
            if fnmatch.fnmatch(path, pattern.replace("**", "*")):
                return True
        return False


def load_ci_config(path: str) -> list[CIEntry]:
    with open(path) as f:
        raw = yamlio.loads(f.read())
    entries = []
    for w in (raw or {}).get("workflows", []) or []:
        trigger = w.get("trigger", "presubmit")
        if trigger not in TRIGGERS:
            raise ValueError(f"{w.get('name')}: bad trigger {trigger!r}; "
                             f"valid: {TRIGGERS}")
        entries.append(CIEntry(
            name=w["name"], workflow=w.get("workflow", w["name"]),
            trigger=trigger, include=list(w.get("include") or ["**"]),
            params=dict(w.get("params") or {})))
    return entries


def select_workflows(changed_files: list[str], entries: list[CIEntry],
                     trigger: str = "presubmit") -> list[CIEntry]:
    """The prow path-filter: every entry of the trigger class whose
    include globs match at least one changed file. Periodic entries
    never depend on the diff."""
    out = []
    for entry in entries:
        if entry.trigger != trigger:
            continue
        if trigger == "periodic" or \
                any(entry.matches(f) for f in changed_files):
            out.append(entry)
    return out


# -- release workflow ---------------------------------------------------------

def release_workflow(component: str, version: str,
                     registry: str = "ghcr.io/kubeflow-tpu",
                     namespace: str = "kubeflow-ci",
                     test_command: Optional[list] = None) -> dict:
    """The image-releaser Workflow (releasing/releaser/components/
    workflows.jsonnet shape): checkout → unit-test → build image → push,
    as a DAG our engine executes. Presubmit pushes go to the CI registry,
    postsubmit to the public one — callers pick via ``registry``."""
    test_command = test_command or ["python", "-m", "pytest", "tests/",
                                    "-x", "-q"]
    image = f"{registry}/{component}:{version}"
    builder = "gcr.io/kaniko-project/executor:v0.10.0"
    return {
        "apiVersion": WORKFLOW_API_VERSION, "kind": WORKFLOW_KIND,
        "metadata": {"name": f"release-{component}-{version}".replace(".", "-"),
                     "namespace": namespace,
                     "labels": {"workflows.kubeflow.org/release": component}},
        "spec": {
            "entrypoint": "release",
            "arguments": {"parameters": [
                {"name": "component", "value": component},
                {"name": "version", "value": version},
                {"name": "image", "value": image},
            ]},
            "templates": [
                {"name": "release", "dag": {"tasks": [
                    {"name": "checkout", "template": "checkout"},
                    {"name": "test", "template": "test",
                     "dependencies": ["checkout"]},
                    {"name": "build", "template": "build",
                     "dependencies": ["test"]},
                    {"name": "push", "template": "push",
                     "dependencies": ["build"]},
                ]}},
                {"name": "checkout", "container": {
                    "image": "alpine/git:1.0.7",
                    "command": ["git", "clone", "--depth=1",
                                "$(workflow.parameters.component)", "/src"]},
                 "activeDeadlineSeconds": 600},
                {"name": "test", "container": {
                    "image": "python:3.12",
                    "command": test_command},
                 "activeDeadlineSeconds": 1800},
                {"name": "build", "container": {
                    "image": builder,
                    "command": ["/kaniko/executor", "--context=/src",
                                f"--destination={image}", "--no-push"]},
                 "activeDeadlineSeconds": 1800},
                {"name": "push", "container": {
                    "image": builder,
                    "command": ["/kaniko/executor", "--context=/src",
                                f"--destination={image}"]},
                 "activeDeadlineSeconds": 1800},
            ],
        },
    }


def repo_ci_config_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "ci_config.yaml")
