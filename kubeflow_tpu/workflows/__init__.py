"""Workflow engine + benchmark harness.

The reference deploys Argo as its workflow engine (kubeflow/argo/
argo.libsonnet: Workflow CRD + controller + UI) and builds two systems on
it: the kubebench benchmark harness (kubeflow/kubebench/
kubebench-job.libsonnet: configurator → job → reporter) and the whole E2E
CI (testing/workflows/). Here the engine is a native reconciler over the
same Workflow shape (DAG of container/resource steps), and kubebench is a
workflow builder + CSV reporter against the KUBEBENCH_* env contract.
"""

from .engine import WorkflowReconciler, WORKFLOW_API_VERSION
from .kubebench import KubebenchJobReconciler, build_kubebench_workflow

__all__ = ["WorkflowReconciler", "WORKFLOW_API_VERSION",
           "KubebenchJobReconciler", "build_kubebench_workflow"]
