"""Auto-update bot: pin a component's image tag to its last source commit.

The reference ships a CI bot that rebuilds the jupyter-web-app image,
rewrites the ksonnet prototype's ``@optionalParam image`` line, and opens
a PR (py/kubeflow/kubeflow/ci/update_jupyter_web_app.py — build_image /
_replace_parameters / update_prototype / all). Same loop here over the
TPU-native layout: a component's image tag IS the last git commit that
touched its source tree, the "prototype" is the manifests module's
``VERSION = "..."`` pin, and the PR is prepared as a branch + commit via
an injectable runner (zero-egress dev: no hub/GCB calls baked in).

    python -m kubeflow_tpu.workflows.image_update jupyter-web-app

Flow (the reference bot's `all`): component source commit → check the
pin → rewrite it → regenerate the rendered examples → branch + commit →
emit the PR payload the caller hands to its forge of choice.
"""

from __future__ import annotations

import logging
import os
import re
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Optional

log = logging.getLogger(__name__)

REGISTRY = "ghcr.io/kubeflow-tpu"

# component → (source tree whose history defines the tag, manifests
# module holding the pin, the PER-COMPONENT pin constant, the image
# names that pin tags). The constant is per component on purpose:
# rewriting the module-wide VERSION would silently retag every other
# image that module builds to a commit of an unrelated source tree. The
# image list is what a consumer of the PR payload must BUILD at the new
# tag — it names the images the manifests actually reference, not the
# component key.
COMPONENT_SOURCES: dict[str, tuple[str, str, str, tuple]] = {
    "jupyter-web-app": ("kubeflow_tpu/webapps",
                        "kubeflow_tpu/manifests/notebooks.py",
                        "JUPYTER_WEB_APP_VERSION",
                        ("jupyter-web-app",)),
    "centraldashboard": ("kubeflow_tpu/webapps",
                         "kubeflow_tpu/manifests/core.py",
                         "CENTRALDASHBOARD_VERSION",
                         ("centraldashboard",)),
    "worker": ("kubeflow_tpu/runtime",
               "kubeflow_tpu/manifests/training.py",
               "WORKER_VERSION",
               ("worker",)),
    "serving": ("kubeflow_tpu/serving",
                "kubeflow_tpu/manifests/serving.py",
                "MODEL_SERVER_VERSION",
                ("tpu-model-server", "serving-http-proxy")),
}


def default_runner(args: list[str], cwd: str) -> str:
    # PYTHONPATH=cwd so child python scripts (examples/regenerate.py)
    # resolve the in-repo package without an install
    env = dict(os.environ, PYTHONPATH=cwd)
    return subprocess.run(args, cwd=cwd, check=True, text=True,
                          capture_output=True, env=env).stdout.strip()


def replace_version(lines: list[str], new: str,
                    pin: str = "VERSION") -> tuple[list[str], str]:
    """Rewrite the named pin constant (the _replace_parameters analog
    over ``// @optionalParam image`` lines). Returns (lines, old)."""
    regex = re.compile(r'^(' + re.escape(pin) + r'\s*=\s*")([^"]*)(")\s*$')
    old = ""
    out = []
    for line in lines:
        m = regex.match(line)
        if m and not old:
            old = m.group(2)
            line = f'{m.group(1)}{new}{m.group(3)}'
        out.append(line)
    if not old:
        raise ValueError(f"no {pin} pin found")
    return out, old


def component_commit(repo_root: str, source_path: str,
                     run: Callable = default_runner) -> str:
    """Last commit touching the component's source tree (the bot's
    last_commit property)."""
    out = run(["git", "log", "-n", "1", "--pretty=format:%h", "--",
               source_path], cwd=repo_root)
    if not out:
        raise ValueError(f"no commits touch {source_path}")
    return out


@dataclass
class UpdateResult:
    component: str
    images: list            # full refs the PR consumer must build+push
    old_tag: str
    new_tag: str
    changed: bool
    branch: str = ""
    pr_title: str = ""
    pr_body: str = ""
    files: list = field(default_factory=list)


def update_component(repo_root: str, component: str,
                     registry: str = REGISTRY,
                     run: Callable = default_runner,
                     commit: bool = True) -> UpdateResult:
    """The bot's `all`: compute the tag, rewrite the pin, regenerate the
    rendered examples, and (optionally) branch + commit, returning the
    PR payload. Idempotent: an up-to-date pin returns changed=False and
    touches nothing."""
    if component not in COMPONENT_SOURCES:
        raise KeyError(f"unknown component {component!r}; known: "
                       f"{sorted(COMPONENT_SOURCES)}")
    source_path, pin_file, pin_name, image_names = \
        COMPONENT_SOURCES[component]
    tag = component_commit(repo_root, source_path, run=run)
    images = [f"{registry}/{name}:{tag}" for name in image_names]

    pin_path = os.path.join(repo_root, pin_file)
    with open(pin_path) as f:
        lines = f.read().split("\n")
    new_lines, old_tag = replace_version(lines, tag, pin=pin_name)
    if old_tag == tag:
        log.info("%s already pinned to %s", component, tag)
        return UpdateResult(component=component, images=images,
                            old_tag=old_tag, new_tag=tag, changed=False)

    # atomic rewrite, the reference bot's tmp+rename
    tmp = pin_path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(new_lines))
    os.replace(tmp, pin_path)
    files = [pin_file]

    # the rendered examples are build OUTPUTS of the builders this pin
    # feeds — regenerate so the sync gate stays green in the PR
    regen = os.path.join(repo_root, "examples", "regenerate.py")
    if os.path.exists(regen):
        import sys
        run([sys.executable, regen], cwd=repo_root)
        files.append("examples")

    branch = f"update-{component}-{tag}"
    title = f"Update {component} image to {tag}"
    body = (f"Automated image pin update.\n\n"
            + "".join(f"* build+push: `{i}`\n" for i in images)
            + f"* previous tag: `{old_tag}`\n"
            f"* source: last commit touching `{source_path}`\n")
    if commit:
        run(["git", "checkout", "-b", branch], cwd=repo_root)
        run(["git", "add", *files], cwd=repo_root)
        run(["git", "commit", "-m", title], cwd=repo_root)
    return UpdateResult(component=component, images=images,
                        old_tag=old_tag, new_tag=tag, changed=True,
                        branch=branch, pr_title=title, pr_body=body,
                        files=files)


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    logging.basicConfig(level=logging.INFO, force=True)
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("component", choices=sorted(COMPONENT_SOURCES))
    p.add_argument("--registry", default=REGISTRY)
    p.add_argument("--repo-root", default=".")
    p.add_argument("--no-commit", action="store_true",
                   help="rewrite the pin only; no branch/commit")
    args = p.parse_args(argv)
    result = update_component(os.path.abspath(args.repo_root),
                              args.component, registry=args.registry,
                              commit=not args.no_commit)
    if not result.changed:
        print(f"{result.component} already pinned to {result.new_tag}")
        return 0
    print(f"updated {result.component}: {result.old_tag} -> "
          f"{result.new_tag} on branch {result.branch}")
    print(result.pr_body)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
