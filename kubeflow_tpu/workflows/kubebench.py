"""Kubebench-equivalent benchmark harness.

The reference's kubebench (kubeflow/kubebench/) runs one Argo Workflow per
benchmark: a configurator step, the launched KF job, and a post-job reporter
that writes a CSV — wired together with PVC roots and the ``KUBEBENCH_*``
env contract (kubebench-job.libsonnet:6-30,53,100-120) plus a
``KubebenchJob`` CRD + operator (kubebench-operator.libsonnet:10-27).

Here:
- ``KubebenchJobReconciler`` expands a KubebenchJob CR into a Workflow on
  our engine: configure → run (resource template creating the training job,
  gang-scheduled by the TPUJob operator) → report.
- ``run_benchmark`` + ``write_csv_report`` are the reporter's actual logic
  (importable in-process and used by ``python -m
  kubeflow_tpu.workflows.kubebench`` inside the reporter container), so the
  CSV format is testable without a cluster.
"""

from __future__ import annotations

import csv
import json
import logging
import os
import time
from typing import Any, Optional

from ..api import k8s
from ..cluster.client import KubeClient, NotFoundError
from ..controllers.runtime import Key, Reconciler, Result
from .engine import (PHASE_FAILED, PHASE_RUNNING, PHASE_SUCCEEDED,
                     WORKFLOW_API_VERSION, WORKFLOW_KIND)

log = logging.getLogger(__name__)

KUBEBENCH_API_VERSION = "kubebench.operator.kubeflow.org/v1alpha1"
KUBEBENCH_KIND = "KubebenchJob"

# the reference's env contract, preserved verbatim
ENV_CONFIG_ROOT = "KUBEBENCH_CONFIG_ROOT"
ENV_DATA_ROOT = "KUBEBENCH_DATA_ROOT"
ENV_EXP_ROOT = "KUBEBENCH_EXP_ROOT"
ENV_EXP_ID = "KUBEBENCH_EXP_ID"
ENV_EXP_PATH = "KUBEBENCH_EXP_PATH"

DEFAULT_IMAGE = "ghcr.io/kubeflow-tpu/kubebench:v0.1.0"

from ..runtime.metrics import METRICS_PATH_ENV  # noqa: E402 (env contract)


def _inject_job_volume(manifest: dict, volume: dict, mount: dict) -> None:
    """Attach the shared kubebench volume to every pod spec in the job
    manifest (any dict holding a "containers" list is a pod spec)."""
    def walk(node):
        if isinstance(node, dict):
            containers = node.get("containers")
            if isinstance(containers, list):
                vols = node.setdefault("volumes", [])
                if not any(v.get("name") == volume["name"] for v in vols):
                    vols.append(volume)
                for c in containers:
                    if isinstance(c, dict):
                        mounts = c.setdefault("volumeMounts", [])
                        if not any(m.get("name") == mount["name"]
                                   for m in mounts):
                            mounts.append(mount)
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)
    walk(manifest)


def _inject_job_env(manifest: dict, env: dict[str, str]) -> None:
    """Append env vars to every container in the job manifest (shape varies
    by job kind, so walk generically — same idiom as katib's injector)."""
    def walk(node):
        if isinstance(node, dict):
            containers = node.get("containers")
            if isinstance(containers, list):
                for c in containers:
                    if isinstance(c, dict):
                        ce = c.setdefault("env", [])
                        present = {e.get("name") for e in ce}
                        for name, value in env.items():
                            if name not in present:
                                ce.append({"name": name, "value": value})
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)
    walk(manifest)


def build_kubebench_workflow(name: str, namespace: str, job_manifest: dict,
                             *, image: str = DEFAULT_IMAGE,
                             exp_root: str = "/kubebench/experiments",
                             config_root: str = "/kubebench/config",
                             data_root: str = "/kubebench/data",
                             report_type: str = "csv",
                             deadline_seconds: int = 3000,
                             pvc: Optional[str] = None) -> dict:
    """The configurator → job → reporter Workflow for one benchmark run
    (kubebench-job.libsonnet shape, with the KF job as a resource step).

    ``pvc`` names the PersistentVolumeClaim mounted at /kubebench in every
    step AND in the benchmarked job — the cross-step file handoff
    (experiment dir, metrics stream, CSV report) rides this shared volume,
    exactly the reference's PVC-roots design (kubebench-job.libsonnet PVC
    params for config/data/experiments).
    """
    import copy
    job_manifest = copy.deepcopy(job_manifest)
    exp_id = name
    exp_path = f"{exp_root}/{exp_id}"
    volume = {"name": "kubebench",
              "persistentVolumeClaim": {"claimName": pvc}} if pvc else None
    mount = {"name": "kubebench", "mountPath": "/kubebench"}
    env = [
        {"name": ENV_CONFIG_ROOT, "value": config_root},
        {"name": ENV_DATA_ROOT, "value": data_root},
        {"name": ENV_EXP_ROOT, "value": exp_root},
        {"name": ENV_EXP_ID, "value": exp_id},
        {"name": ENV_EXP_PATH, "value": exp_path},
    ]
    job_kind = job_manifest.get("kind", "TPUJob")
    # the benchmarked job streams its per-step metrics into the experiment
    # dir (shared volume in a real cluster); the reporter aggregates that
    # file — the post-job CSV reporter contract
    _inject_job_env(job_manifest, dict(
        [(e["name"], e["value"]) for e in env] +
        [(METRICS_PATH_ENV, f"{exp_path}/metrics.jsonl")]))
    if volume:
        _inject_job_volume(job_manifest, volume, mount)
    step_container_extra = {"volumeMounts": [mount]} if volume else {}
    wf_spec_extra = {"volumes": [volume]} if volume else {}
    return {
        "apiVersion": WORKFLOW_API_VERSION, "kind": WORKFLOW_KIND,
        "metadata": {"name": f"{name}-wf", "namespace": namespace},
        "spec": {
            "entrypoint": "kubebench",
            **wf_spec_extra,
            "templates": [
                {"name": "kubebench", "dag": {"tasks": [
                    {"name": "configure", "template": "configurator"},
                    {"name": "run", "template": "run-job",
                     "dependencies": ["configure"]},
                    {"name": "report", "template": "reporter",
                     "dependencies": ["run"]},
                ]}},
                {"name": "configurator",
                 "activeDeadlineSeconds": deadline_seconds,
                 "container": {
                     "image": image,
                     "command": ["python", "-m",
                                 "kubeflow_tpu.workflows.kubebench"],
                     "args": ["configure"], "env": env,
                     **step_container_extra}},
                {"name": "run-job",
                 "activeDeadlineSeconds": deadline_seconds,
                 "resource": {
                     "action": "create",
                     "manifest": job_manifest,
                     "successCondition": "condition:Succeeded=True",
                     "failureCondition": "condition:Failed=True"}},
                {"name": "reporter",
                 "activeDeadlineSeconds": deadline_seconds,
                 "container": {
                     "image": image,
                     "command": ["python", "-m",
                                 "kubeflow_tpu.workflows.kubebench"],
                     "args": ["report", f"--report-type={report_type}",
                              f"--job-kind={job_kind}"],
                     "env": env,
                     **step_container_extra}},
            ],
        },
    }


class KubebenchJobReconciler(Reconciler):
    """KubebenchJob CR → owned Workflow; status mirrors the workflow phase
    (the kubebench-operator's job, kubebench-operator.libsonnet:10-27)."""

    primary = (KUBEBENCH_API_VERSION, KUBEBENCH_KIND)
    owns = [(WORKFLOW_API_VERSION, WORKFLOW_KIND)]

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            kb = client.get(KUBEBENCH_API_VERSION, KUBEBENCH_KIND, ns, name)
        except NotFoundError:
            return Result()
        status = kb.setdefault("status", {})
        if status.get("phase") in (PHASE_SUCCEEDED, PHASE_FAILED):
            return Result()
        spec = kb.get("spec", {})
        job_manifest = spec.get("jobTemplate")
        if not job_manifest:
            status["phase"] = PHASE_FAILED
            status["message"] = "spec.jobTemplate is required"
            client.update_status(kb)
            return Result()

        wf_name = f"{name}-wf"
        wf = client.get_or_none(WORKFLOW_API_VERSION, WORKFLOW_KIND, ns,
                                wf_name)
        if wf is None:
            import copy
            job = copy.deepcopy(job_manifest)
            job.setdefault("metadata", {}).setdefault("name", f"{name}-job")
            job["metadata"].setdefault("namespace", ns)
            wf = build_kubebench_workflow(
                name, ns, job,
                image=spec.get("image", DEFAULT_IMAGE),
                exp_root=spec.get("experimentsRoot",
                                  "/kubebench/experiments"),
                report_type=spec.get("reportType", "csv"),
                deadline_seconds=int(spec.get("activeDeadlineSeconds", 3000)),
                pvc=spec.get("pvcName"))
            k8s.set_owner(wf, kb)
            client.create(wf)
            status["phase"] = PHASE_RUNNING
            status["workflow"] = wf_name
            client.update_status(kb)
            return Result()

        wf_phase = wf.get("status", {}).get("phase")
        if wf_phase in (PHASE_SUCCEEDED, PHASE_FAILED, "Error"):
            status["phase"] = PHASE_SUCCEEDED if wf_phase == PHASE_SUCCEEDED \
                else PHASE_FAILED
            status["message"] = wf.get("status", {}).get("message", "")
            status["nodes"] = wf.get("status", {}).get("nodes", {})
            client.update_status(kb)
        elif status.get("phase") != PHASE_RUNNING:
            status["phase"] = PHASE_RUNNING
            client.update_status(kb)
        return Result()


# ---------------------------------------------------------------------------
# Reporter / configurator logic (runs inside the workflow's containers, and
# in-process for local benchmarking + tests)

def experiment_paths(env: Optional[dict] = None) -> dict[str, str]:
    env = env if env is not None else dict(os.environ)
    exp_path = env.get(ENV_EXP_PATH) or os.path.join(
        env.get(ENV_EXP_ROOT, "/kubebench/experiments"),
        env.get(ENV_EXP_ID, "exp"))
    return {"exp_path": exp_path,
            "config": env.get(ENV_CONFIG_ROOT, "/kubebench/config"),
            "data": env.get(ENV_DATA_ROOT, "/kubebench/data"),
            "exp_id": env.get(ENV_EXP_ID, "exp")}


def configure(env: Optional[dict] = None) -> str:
    """Configurator step: materialize the experiment directory skeleton
    (the reference's configurator templates the KF job from ksonnet; our
    job is rendered by the operator, so configure just prepares the roots)."""
    paths = experiment_paths(env)
    os.makedirs(paths["exp_path"], exist_ok=True)
    marker = os.path.join(paths["exp_path"], "experiment.json")
    with open(marker, "w") as f:
        json.dump({"id": paths["exp_id"], "created": time.time()}, f)
    return paths["exp_path"]


def write_csv_report(path: str, rows: list[dict[str, Any]]) -> str:
    """The csv-reporter: one row per run, stable header union (the
    post-job reporter output kubebench-job.libsonnet:100-120 points at)."""
    if not rows:
        raise ValueError("no rows to report")
    fieldnames: list[str] = []
    for r in rows:
        for k in r:
            if k not in fieldnames:
                fieldnames.append(k)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fieldnames)
        w.writeheader()
        for r in rows:
            w.writerow(r)
    return path


def report_from_metrics(metrics_path: str, *, job_kind: str = "TPUJob",
                        warmup: int = 1,
                        env: Optional[dict] = None) -> dict[str, Any]:
    """Aggregate the benchmarked job's metrics.jsonl (MetricsLogger stream,
    runtime/metrics.py StepStats rows) into the reporter row. This is the
    post-job reporter reading the run that actually happened — not a rerun."""
    rows = []
    with open(metrics_path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if not rows:
        raise ValueError(f"no step records in {metrics_path}")
    # out-of-band event records (eval passes) carry no timing; fold their
    # metrics into the nearest preceding step record and drop the row
    events = [r for r in rows if r.get("event")]
    rows = [r for r in rows if not r.get("event")]
    if not rows:
        raise ValueError(f"no timed step records in {metrics_path}")
    for ev in events:
        # an event earlier than every timed record folds into the FIRST
        # record (nearest by step), not the last
        tgt = max((r for r in rows if r["step"] <= ev.get("step", 0)),
                  key=lambda r: r["step"], default=rows[0])
        tgt.setdefault("metrics", {}).update(ev.get("metrics") or {})
    steady = rows[warmup:] if len(rows) > warmup else rows
    # records may be multi-step windows (worker sync_every): weight by the
    # number of device steps each record covers
    weights = [int(r.get("window", 1)) for r in steady]
    total_w = sum(weights) or 1
    mean_t = sum(r["step_time_s"] * w
                 for r, w in zip(steady, weights)) / total_w
    ex_s = sum(r.get("examples_per_sec", 0.0) * w
               for r, w in zip(steady, weights)) / total_w
    last = rows[-1]
    envd = env if env is not None else dict(os.environ)
    # StepStats.to_dict flattens model metrics alongside the timing fields
    timing_keys = {"step", "step_time_s", "examples_per_sec", "window"}
    model_metrics = dict(last.get("metrics") or {})
    model_metrics.update({k: v for k, v in last.items()
                          if k not in timing_keys and k != "metrics"
                          and isinstance(v, (int, float))})
    return {
        "experiment": envd.get(ENV_EXP_ID, "exp"),
        "job_kind": job_kind,
        "steps": last.get("step", len(rows)),
        "examples_per_sec": round(ex_s, 2),
        "mean_step_time_s": round(mean_t, 6),
        **{f"metric_{k}": round(float(v), 6)
           for k, v in sorted(model_metrics.items())},
    }


def _device_columns(examples_per_sec: float, workload: str) -> dict[str, Any]:
    """Hardware context + MFU/vs_baseline columns for a matrix row — the
    kubebench CSVs must say WHAT chip produced a number and how it sits
    against the recorded first-light baseline (r4 verdict: 'honest
    labels')."""
    import jax

    from ..utils.chips import BASELINE_IMG_S, resnet50_train_mfu
    dev = jax.devices()[0]
    n_chips = len(jax.devices())
    per_chip = examples_per_sec / n_chips
    cols: dict[str, Any] = {
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "chips": n_chips,
    }
    if workload.startswith("resnet50"):
        mfu = resnet50_train_mfu(per_chip, dev)
        cols["mfu"] = round(mfu, 4) if mfu is not None else ""
        cols["vs_baseline"] = round(per_chip / BASELINE_IMG_S, 3)
    return cols


def run_benchmark(workload: str = "resnet50", steps: int = 10,
                  global_batch: int = 32, report_path: Optional[str] = None,
                  **train_kwargs) -> dict[str, Any]:
    """In-process benchmark: run the real training loop and produce the
    reporter row (the tf-cnn-equivalent vehicle, SURVEY.md §6)."""
    from ..runtime.worker import train
    result = train(workload=workload, steps=steps, global_batch=global_batch,
                   **train_kwargs)
    label = workload + ("-fused" if train_kwargs.get(
        "workload_kwargs", {}).get("fused") else "")
    row = {
        "experiment": os.environ.get(ENV_EXP_ID, "local"),
        "workload": label,
        "steps": result.steps,
        "global_batch": global_batch,
        "examples_per_sec": round(result.examples_per_sec, 2),
        "mean_step_time_s": round(result.mean_step_time_s, 6),
        "first_window_s": round(result.first_window_s, 3),
        **_device_columns(result.examples_per_sec, label),
        **{f"metric_{k}": round(float(v), 6)
           for k, v in result.final_metrics.items()},
    }
    if report_path:
        write_csv_report(report_path, [row])
    return row


def _katib_study_benchmark(steps: int = 3, global_batch: int = 8,
                           trials: int = 2, **train_kwargs) -> dict[str, Any]:
    """In-process Katib study over training trials: the 'StudyJob search
    over TFJob trials' BASELINE config, using the real suggestion engine
    + the real train loop per trial."""
    from ..katib.suggestion import ParameterConfig, make_suggestion
    from ..runtime.worker import train

    params = [ParameterConfig(name="learning_rate", parametertype="double",
                              min=0.01, max=0.3)]
    sugg = make_suggestion("random", params, seed=0)
    best = None
    for _ in range(trials):
        assignment = sugg.suggest(1)[0]
        lr = float(assignment["learning_rate"])
        result = train(steps=steps, global_batch=global_batch,
                       learning_rate=lr, **train_kwargs)
        loss = result.final_metrics.get("loss", float("inf"))
        sugg.observe(assignment, -loss)  # engine maximizes
        if best is None or loss < best["metric_loss"]:
            best = {"metric_loss": loss, "learning_rate": lr,
                    "examples_per_sec": result.examples_per_sec}
    return {
        "experiment": os.environ.get(ENV_EXP_ID, "local"),
        "workload": "katib-study/resnet50",
        "steps": steps * trials,
        "global_batch": global_batch,
        "examples_per_sec": round(best["examples_per_sec"], 2),
        "mean_step_time_s": 0.0,
        **_device_columns(best["examples_per_sec"], "katib-study"),
        "metric_loss": round(best["metric_loss"], 6),
        "metric_best_learning_rate": round(best["learning_rate"], 6),
    }


# The BASELINE.json config matrix (BASELINE.md "Config matrix to cover"),
# mapped onto the TPU-native execution path. Each entry = (job_kind the
# reference ran it as, runner kwargs); the runner is run_benchmark unless
# the entry names its own callable. Dims are scaled by the caller (full
# size on hardware, tiny on the CPU mesh in tests).
CONFIG_MATRIX: dict[str, dict[str, Any]] = {
    # TFJob tf-cnn ResNet-50 (1 chief + 1 worker, CPU — tf_job_simple)
    "tf_job_simple": {"job_kind": "TFJob", "workload": "resnet50"},
    # TFJob data-parallel allreduce (ResNet-50, 8-worker): same pjit path,
    # DP over every mesh device (XLA allreduce over ICI)
    "tf_job_dp_allreduce": {"job_kind": "TFJob", "workload": "resnet50"},
    # PyTorchJob DDP equivalent — DDP's allreduce IS the DP sharding here
    "pytorch_ddp": {"job_kind": "PyTorchJob", "workload": "resnet50"},
    # MPIJob Horovod equivalent — NCCL ring → ICI collective
    "mpi_horovod": {"job_kind": "MPIJob", "workload": "resnet50"},
    # the opt-in ghost-BN fused-block variant (ops/fused_block_train):
    # same model FLOPs, fewer HBM bytes — the PERF.md item-1 path
    "tf_job_fused_blocks": {"job_kind": "TFJob", "workload": "resnet50",
                            "workload_kwargs": {"fused": True}},
    # Katib StudyJob search over trials
    "katib_study": {"job_kind": "StudyJob", "runner": "katib"},
}


def benchmark_matrix(out_dir: str, *, steps: int = 5, global_batch: int = 16,
                     configs: Optional[list[str]] = None,
                     **train_kwargs) -> dict[str, dict]:
    """Drive the BASELINE config matrix; one CSV per config (the kubebench
    'one workflow per benchmark' shape, kubebench-job.libsonnet:6-30)."""
    os.makedirs(out_dir, exist_ok=True)
    rows = {}
    for name in (configs or list(CONFIG_MATRIX)):
        cfg = dict(CONFIG_MATRIX[name])
        job_kind = cfg.pop("job_kind")
        report = os.path.join(out_dir, f"{name}.csv")
        # a config's workload_kwargs (e.g. fused) merge UNDER the
        # caller's dims (image_size on the CPU mesh) instead of clashing
        kwargs = dict(train_kwargs)
        cfg_wk = cfg.pop("workload_kwargs", None)
        if cfg_wk:
            kwargs["workload_kwargs"] = {**cfg_wk,
                                         **kwargs.get("workload_kwargs", {})}
        if cfg.pop("runner", None) == "katib":
            row = _katib_study_benchmark(steps=steps,
                                         global_batch=global_batch,
                                         **kwargs)
        else:
            row = run_benchmark(steps=steps, global_batch=global_batch,
                                **cfg, **kwargs)
        row["job_kind"] = job_kind
        write_csv_report(report, [row])
        rows[name] = row
        log.info("config %s (%s): %s", name, job_kind, row)
    return rows


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    logging.basicConfig(level=logging.INFO, force=True)
    p = argparse.ArgumentParser(description="kubebench step entrypoint")
    p.add_argument("step", choices=["configure", "report", "matrix"])
    p.add_argument("--out-dir", default="bench-matrix",
                   help="matrix: directory receiving one CSV per config")
    p.add_argument("--report-type", default="csv")
    p.add_argument("--job-kind", default="TPUJob")
    p.add_argument("--local", action="store_true",
                   help="run the workload in-process instead of reporting "
                        "on a finished job's metrics (dev benchmarking)")
    p.add_argument("--workload", default="resnet50")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--global-batch", type=int, default=32)
    args = p.parse_args(argv)
    if args.step == "configure":
        path = configure()
        log.info("experiment configured at %s", path)
        return 0
    if args.step == "matrix":
        rows = benchmark_matrix(args.out_dir, steps=args.steps,
                                global_batch=args.global_batch)
        log.info("matrix complete: %d configs -> %s", len(rows), args.out_dir)
        return 0
    paths = experiment_paths()
    report = os.path.join(paths["exp_path"], "report.csv")
    if args.local:
        row = run_benchmark(workload=args.workload, steps=args.steps,
                            global_batch=args.global_batch,
                            report_path=report)
    else:
        metrics_path = os.path.join(paths["exp_path"], "metrics.jsonl")
        if not os.path.exists(metrics_path):
            log.error("no metrics at %s — did the job run with %s set? "
                      "(use --local for an in-process benchmark)",
                      metrics_path, METRICS_PATH_ENV)
            return 1
        row = report_from_metrics(metrics_path, job_kind=args.job_kind)
        write_csv_report(report, [row])
    log.info("report written to %s: %s", report, row)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
