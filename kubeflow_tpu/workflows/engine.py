"""Workflow engine: DAG-of-steps reconciler (the Argo controller analog).

The reference installs Argo (kubeflow/argo/argo.libsonnet:13-37 Workflow CRD,
:112 controller, :194-231 UI/RBAC) and expresses kubebench runs and the whole
CI system as Workflows (kubeflow/kubebench/kubebench-job.libsonnet,
testing/workflows/components/workflows.libsonnet:33-60 kfTests DAG). This
reconciler supports the subset those consumers use:

- ``spec.entrypoint`` naming a template of ``dag.tasks`` (with
  ``dependencies``) or serial ``steps``.
- **container templates** → one Pod per task, owner-ref'd to the Workflow.
- **resource templates** → create an arbitrary manifest (the way kubebench
  launches its KF job) and wait for ``successCondition`` /
  ``failureCondition`` (``status.phase=X`` or ``condition:Type=True`` forms).
- ``spec.arguments.parameters`` substituted as ``$(workflow.parameters.N)``,
  plus ``$(workflow.name)`` / ``$(workflow.namespace)``.
- fail-fast: a failed task fails the Workflow; unreached tasks are Omitted.
- ``activeDeadlineSeconds`` per task — the only wall-time budget the
  reference CI has (SURVEY.md §6).

Status mirrors Argo's: ``status.phase`` ∈ Pending/Running/Succeeded/Failed
and per-node records under ``status.nodes``.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Optional

from ..api import k8s
from ..cluster.client import KubeClient, NotFoundError
from ..controllers.runtime import (Key, Reconciler, Result,
                                   status_snapshot)

log = logging.getLogger(__name__)

WORKFLOW_API_VERSION = "argoproj.io/v1alpha1"
WORKFLOW_KIND = "Workflow"
TASK_LABEL = "workflows.kubeflow.org/task"
WORKFLOW_LABEL = "workflows.kubeflow.org/workflow"
DEADLINE_ANNOTATION = "workflows.kubeflow.org/deadline-at"

PHASE_PENDING = "Pending"
PHASE_RUNNING = "Running"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"
PHASE_ERROR = "Error"
PHASE_OMITTED = "Omitted"

TERMINAL = (PHASE_SUCCEEDED, PHASE_FAILED, PHASE_ERROR, PHASE_OMITTED)


def check_condition_expr(obj: dict, expr: str) -> bool:
    """Evaluate a success/failureCondition expression against an object.

    Forms: ``status.phase = Succeeded`` (dotted path compare, whitespace
    optional) and ``condition: Type = True`` (status.conditions lookup, the
    shape our CRDs and Argo's resource templates both use).
    """
    expr = expr.strip()
    if expr.startswith("condition:"):
        rest = expr[len("condition:"):]
        ctype, _, want = rest.partition("=")
        want = want.strip() or "True"
        c = k8s.get_condition(obj, ctype.strip())
        return c is not None and c.get("status") == want
    path, _, want = expr.partition("=")
    want = want.strip()
    node: Any = obj
    for part in path.strip().split("."):
        if not isinstance(node, dict) or part not in node:
            return False
        node = node[part]
    return str(node) == want


class WorkflowReconciler(Reconciler):
    primary = (WORKFLOW_API_VERSION, WORKFLOW_KIND)
    # resource templates can create arbitrary kinds; the common ones are
    # watched for event-driven sync, everything else is covered by the
    # Running-resource polling requeue in _sync_node
    owns = [("v1", "Pod"),
            ("tpu.kubeflow.org/v1alpha1", "TPUJob"),
            ("kubeflow.org/v1beta2", "TFJob"),
            ("kubeflow.org/v1beta2", "PyTorchJob"),
            ("kubeflow.org/v1alpha1", "MPIJob")]

    def __init__(self, clock=time.time, poll_interval: float = 0.25):
        # wall clock, not monotonic: deadlineAt/startedAt persist into
        # status and must survive controller restarts
        self.clock = clock
        # requeue delay for state no watch event covers (unwatched resource
        # kinds, pending deadlines)
        self.poll_interval = poll_interval

    # -- template plumbing ---------------------------------------------------

    def _templates(self, spec: dict) -> dict[str, dict]:
        return {t["name"]: t for t in spec.get("templates", []) or []}

    def _task_list(self, wf: dict) -> Optional[list[dict]]:
        """Flatten the entrypoint into [{name, template, dependencies}].
        ``steps`` (serial groups) become a dependency chain, Argo semantics:
        each group runs after the previous group completes."""
        spec = wf.get("spec", {})
        templates = self._templates(spec)
        entry = templates.get(spec.get("entrypoint", ""))
        if entry is None:
            return None
        def entry_of(t: dict, deps: list[str]) -> dict:
            if "name" not in t or "template" not in t:
                raise ValueError(f"task entry needs name and template: {t}")
            return {"name": t["name"], "template": t["template"],
                    "dependencies": deps}

        if "dag" in entry:
            return [entry_of(t, list(t.get("dependencies") or []))
                    for t in entry["dag"].get("tasks", []) or []]
        if "steps" in entry:
            tasks = []
            prev_group: list[str] = []
            for group in entry.get("steps", []) or []:
                group = group if isinstance(group, list) else [group]
                for s in group:
                    tasks.append(entry_of(s, list(prev_group)))
                prev_group = [s["name"] for s in group]
            return tasks
        # a bare container/resource entrypoint is a single-task workflow
        if "container" in entry or "resource" in entry:
            return [{"name": entry["name"], "template": entry["name"],
                     "dependencies": []}]
        return None

    def _params(self, wf: dict) -> dict[str, Any]:
        out = {"workflow.name": k8s.name_of(wf),
               "workflow.namespace": k8s.namespace_of(wf, "default")}
        args = (wf.get("spec", {}).get("arguments") or {})
        for p in args.get("parameters", []) or []:
            out[f"workflow.parameters.{p['name']}"] = p.get("value")
        return out

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            wf = client.get(WORKFLOW_API_VERSION, WORKFLOW_KIND, ns, name)
        except NotFoundError:
            return Result()
        status = wf.setdefault("status", {})
        if status.get("phase") in (PHASE_SUCCEEDED, PHASE_FAILED, PHASE_ERROR):
            return Result()
        status_before = status_snapshot(status)

        try:
            tasks = self._task_list(wf)
        except ValueError as e:
            self._finish(client, wf, PHASE_ERROR, str(e))
            return Result()
        if tasks is None:
            self._finish(client, wf, PHASE_ERROR,
                         "entrypoint template missing or not dag/steps/container")
            return Result()
        names = [t["name"] for t in tasks]
        if len(set(names)) != len(names):
            self._finish(client, wf, PHASE_ERROR, "duplicate task names")
            return Result()
        by_name = {t["name"]: t for t in tasks}
        for t in tasks:
            for dep in t["dependencies"]:
                if dep not in by_name:
                    self._finish(client, wf, PHASE_ERROR,
                                 f"task {t['name']} depends on unknown {dep}")
                    return Result()

        templates = self._templates(wf.get("spec", {}))
        params = self._params(wf)
        nodes: dict[str, dict] = dict(status.get("nodes", {}))
        need_requeue = False

        # 1. advance running nodes from their pods / resources
        for t in tasks:
            node = nodes.get(t["name"])
            if not node or node["phase"] in TERMINAL:
                continue
            tick = self._sync_node(client, wf, t, templates[t["template"]],
                                   node)
            need_requeue = need_requeue or tick

        # 2. launch ready tasks
        failed = any(n["phase"] in (PHASE_FAILED, PHASE_ERROR)
                     for n in nodes.values())
        if not failed:
            for t in tasks:
                if t["name"] in nodes:
                    continue
                deps = [nodes.get(d, {}).get("phase") for d in t["dependencies"]]
                if all(p == PHASE_SUCCEEDED for p in deps):
                    tmpl = templates.get(t["template"])
                    if tmpl is None:
                        nodes[t["name"]] = {"phase": PHASE_ERROR,
                                            "message": f"unknown template "
                                                       f"{t['template']}"}
                        failed = True
                        break
                    nodes[t["name"]] = self._launch(client, wf, t, tmpl,
                                                    params)

        # 3. failure propagation: mark unreachable tasks Omitted
        failed = any(n["phase"] in (PHASE_FAILED, PHASE_ERROR)
                     for n in nodes.values())
        if failed:
            for t in tasks:
                if t["name"] not in nodes:
                    nodes[t["name"]] = {"phase": PHASE_OMITTED,
                                        "message": "upstream failure"}

        # 4. roll up workflow phase
        phases = [nodes.get(t["name"], {}).get("phase") for t in tasks]
        status["nodes"] = nodes
        if failed and all(p in TERMINAL for p in phases):
            self._finish(client, wf, PHASE_FAILED, "a task failed", nodes)
            return Result()
        if all(p == PHASE_SUCCEEDED for p in phases):
            self._finish(client, wf, PHASE_SUCCEEDED, "all tasks succeeded",
                         nodes)
            return Result()
        status["phase"] = PHASE_RUNNING
        if status_snapshot(status) != status_before:
            self._write_status(client, wf, status)
        return Result(requeue_after=self.poll_interval) if need_requeue \
            else Result()

    # -- node lifecycle ------------------------------------------------------

    def _pod_name(self, wf: dict, task: str) -> str:
        return f"{k8s.name_of(wf)}-{task}"

    def _launch(self, client: KubeClient, wf: dict, task: dict, tmpl: dict,
                params: dict) -> dict:
        ns = k8s.namespace_of(wf, "default")
        tmpl = k8s.substitute_params(tmpl, params)
        deadline = tmpl.get("activeDeadlineSeconds")
        node: dict[str, Any] = {"phase": PHASE_RUNNING,
                                "template": task["template"],
                                "startedAt": self.clock()}
        if deadline:
            node["deadlineAt"] = self.clock() + float(deadline)
        if "container" in tmpl:
            # volumes: template-level plus workflow-level (Argo spec.volumes
            # — how kubebench shares its PVC roots across steps)
            volumes = list(wf.get("spec", {}).get("volumes") or []) + \
                list(tmpl.get("volumes") or [])
            pod_spec = {"restartPolicy": "Never",
                        "containers": [dict(tmpl["container"],
                                            name=task["name"])]}
            if volumes:
                pod_spec["volumes"] = volumes
            pod = {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {
                    "name": self._pod_name(wf, task["name"]), "namespace": ns,
                    "labels": {WORKFLOW_LABEL: k8s.name_of(wf),
                               TASK_LABEL: task["name"]},
                },
                "spec": pod_spec,
            }
            k8s.set_owner(pod, wf)
            try:
                client.create(pod)
            except Exception as e:  # noqa: BLE001 - surfaced as node error
                return {"phase": PHASE_ERROR, "message": str(e)}
            node["podName"] = pod["metadata"]["name"]
            node["type"] = "Pod"
            return node
        if "resource" in tmpl:
            res = tmpl["resource"]
            manifest = res.get("manifest")
            if isinstance(manifest, str):
                import yaml
                manifest = yaml.safe_load(manifest)
            if not isinstance(manifest, dict):
                return {"phase": PHASE_ERROR,
                        "message": "resource template needs a manifest"}
            manifest.setdefault("metadata", {}).setdefault("namespace", ns)
            k8s.set_owner(manifest, wf)
            action = res.get("action", "create")
            try:
                if action == "apply":
                    client.apply(manifest)
                else:
                    client.create(manifest)
            except Exception as e:  # noqa: BLE001 - surfaced as node error
                return {"phase": PHASE_ERROR, "message": str(e)}
            node["type"] = "Resource"
            node["resource"] = list(k8s.key_of(manifest))
            node["successCondition"] = res.get("successCondition",
                                               "status.phase=Succeeded")
            if res.get("failureCondition"):
                node["failureCondition"] = res["failureCondition"]
            return node
        return {"phase": PHASE_ERROR,
                "message": f"template {task['template']} has neither "
                           f"container nor resource"}

    def _sync_node(self, client: KubeClient, wf: dict, task: dict,
                   tmpl: dict, node: dict) -> bool:
        """Advance one Running node; returns True when it needs polling (a
        deadline is pending, or a resource kind no watch covers)."""
        needs_poll = False
        if node.get("type") == "Pod":
            ns = k8s.namespace_of(wf, "default")
            pod = client.get_or_none("v1", "Pod", ns, node.get("podName", ""))
            if pod is None:
                node["phase"] = PHASE_ERROR
                node["message"] = "pod disappeared"
                return False
            phase = pod.get("status", {}).get("phase")
            if phase == "Succeeded":
                node["phase"] = PHASE_SUCCEEDED
            elif phase == "Failed":
                node["phase"] = PHASE_FAILED
                node["message"] = pod.get("status", {}).get("message",
                                                            "pod failed")
        elif node.get("type") == "Resource":
            av, kind, rns, rname = node["resource"]
            obj = client.get_or_none(av, kind, rns, rname)
            if obj is None:
                node["phase"] = PHASE_ERROR
                node["message"] = f"{kind} {rns}/{rname} disappeared"
                return False
            if node.get("failureCondition") and \
                    check_condition_expr(obj, node["failureCondition"]):
                node["phase"] = PHASE_FAILED
                node["message"] = f"failureCondition met on {kind} {rname}"
            elif check_condition_expr(obj, node["successCondition"]):
                node["phase"] = PHASE_SUCCEEDED
            # unwatched kinds deliver no events, so poll while running
            needs_poll = (av, kind) not in self.owns
        # deadline is checked only after the state read: work that finished
        # in time must win even when the reconcile lands past the deadline
        if node["phase"] == PHASE_RUNNING and node.get("deadlineAt"):
            if self.clock() > node["deadlineAt"]:
                node["phase"] = PHASE_FAILED
                node["message"] = "deadline exceeded"
                self._kill_node(client, wf, node)
                return False
            needs_poll = True
        return needs_poll and node["phase"] == PHASE_RUNNING

    def _kill_node(self, client: KubeClient, wf: dict, node: dict) -> None:
        ns = k8s.namespace_of(wf, "default")
        try:
            if node.get("type") == "Pod" and node.get("podName"):
                client.delete("v1", "Pod", ns, node["podName"])
            elif node.get("type") == "Resource":
                av, kind, rns, rname = node["resource"]
                client.delete(av, kind, rns, rname)
        except NotFoundError:
            pass

    # -- status --------------------------------------------------------------

    def _write_status(self, client: KubeClient, wf: dict, status: dict) -> None:
        fresh = client.get(WORKFLOW_API_VERSION, WORKFLOW_KIND,
                           k8s.namespace_of(wf, "default"), k8s.name_of(wf))
        fresh["status"] = status
        client.update_status(fresh)

    def _finish(self, client: KubeClient, wf: dict, phase: str, message: str,
                nodes: Optional[dict] = None) -> None:
        status = dict(wf.get("status", {}))
        status["phase"] = phase
        status["message"] = message
        if nodes is not None:
            status["nodes"] = nodes
        k8s.set_condition(wf, k8s.Condition(
            "Completed", "True", phase, message))
        status["conditions"] = wf["status"].get("conditions", [])
        self._write_status(client, wf, status)
        log.info("workflow %s/%s %s: %s", k8s.namespace_of(wf, "default"),
                 k8s.name_of(wf), phase, message)
