"""Built-in workloads.

- ``resnet``: ResNet-50 — the flagship benchmark model, the analog of the
  reference's tf_cnn_benchmarks ResNet-50 TFJob workload
  (tf-controller-examples/tf-cnn/, kubeflow/examples/prototypes/
  tf-job-simple-v1.jsonnet:11-47).
- ``transformer``: decoder-only LM with logical sharding annotations —
  the TP/PP/SP/EP showcase (no analog in the reference; SURVEY.md §2.5 row 5).
"""

# The supported ResNet family (tf_cnn_benchmarks --model surface). Defined
# here — not in .resnet — so the worker/serving registries can enumerate the
# family without importing flax; resnet.STAGE_SIZES is checked against this
# at import time.
RESNET_DEPTHS = (18, 34, 50, 101, 152)
