"""Decoder-only Transformer LM with logical sharding annotations.

The parallelism showcase the reference has no analog for (SURVEY.md §2.5
row 5): every parameter carries logical axis names which LogicalRules lower
to mesh axes — the same model runs DP, FSDP, TP, SP or any mix by changing
the TPUJob sharding spec, with XLA inserting the collectives.

TPU design notes:
- bfloat16 activations/compute, float32 params + layernorm.
- attention QKV as one fused projection (one big MXU matmul).
- sequence-parallel ready: activations carry a "sequence" logical axis;
  with sharding.sequence > 1 XLA shards the sequence dim and the attention
  block computes over gathered K/V (ring attention kernel in ops/ replaces
  the gather for long context).
- causal mask built with lax-friendly iota, no dynamic shapes.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel.sharding_rules import TRANSFORMER_RULES


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    embed_dim: int = 768
    num_heads: int = 12
    head_dim: int = 64
    mlp_dim: int = 3072
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    remat: bool = False          # jax.checkpoint each block (HBM for FLOPs)
    # attention implementation: "einsum" (XLA-fused reference), "flash"
    # (Pallas fused kernel, ops/flash_attention), or "ring" (sequence-
    # parallel ring attention over mesh axis "sequence" for long context)
    attention: str = "einsum"
    mesh: Any = None             # required for attention="ring"
    # mixture-of-experts: num_experts > 0 swaps the dense MLP for MoEMLP
    # (models/moe.py) with expert-parallel weights (mesh axis "expert")
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    def __post_init__(self):
        valid = ("einsum", "flash", "ring")
        if self.attention not in valid:
            raise ValueError(
                f"attention={self.attention!r} not in {valid}")

    @classmethod
    def tiny(cls) -> "TransformerConfig":
        return cls(vocab_size=256, num_layers=2, embed_dim=64, num_heads=4,
                   head_dim=16, mlp_dim=128, max_seq_len=128)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, S, E = x.shape
        qkv = nn.DenseGeneral(
            (3, cfg.num_heads, cfg.head_dim), axis=-1, dtype=cfg.dtype,
            param_dtype=jnp.float32, use_bias=False, name="qkv")(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.attention == "flash":
            from ..ops import flash_attention
            out = flash_attention(q, k, v, causal=True)
        elif cfg.attention == "ring":
            from ..ops import ring_attention
            assert cfg.mesh is not None, "attention='ring' needs cfg.mesh"
            out = ring_attention(q, k, v, mesh=cfg.mesh, causal=True)
        else:
            q = q / jnp.sqrt(cfg.head_dim).astype(cfg.dtype)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k)
            mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
            logits = jnp.where(mask[None, None], logits,
                               jnp.finfo(cfg.dtype).min)
            probs = jax.nn.softmax(
                logits.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(
            E, axis=(-2, -1), dtype=cfg.dtype, param_dtype=jnp.float32,
            use_bias=False, name="out")(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype, param_dtype=jnp.float32,
                     use_bias=False, name="wi")(x)
        h = nn.gelu(h)
        return nn.Dense(x.shape[-1], dtype=cfg.dtype, param_dtype=jnp.float32,
                        use_bias=False, name="wo")(h)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + Attention(cfg, name="attn")(y)
        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        if cfg.num_experts > 0:
            from .moe import MoEMLP
            ff = MoEMLP(num_experts=cfg.num_experts, mlp_dim=cfg.mlp_dim,
                        top_k=cfg.moe_top_k,
                        capacity_factor=cfg.moe_capacity_factor,
                        aux_loss_weight=cfg.moe_aux_weight,
                        dtype=cfg.dtype, name="moe")(y)
        else:
            ff = MLP(cfg, name="mlp")(y)
        return x + ff


class TransformerLM(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                     param_dtype=jnp.float32, dtype=cfg.dtype,
                     name="tok_embed")(tokens)
        pos = nn.Embed(cfg.max_seq_len, cfg.embed_dim,
                       param_dtype=jnp.float32, dtype=cfg.dtype,
                       name="pos_embed")(jnp.arange(tokens.shape[1]))
        x = x + pos[None]
        block = Block
        if cfg.remat:
            block = nn.remat(Block)
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                          param_dtype=jnp.float32, use_bias=False,
                          name="head")(x)
        return logits


class _Embedder(nn.Module):
    """Token + position embedding (the pre-pipeline stage-0 prologue)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim, param_dtype=jnp.float32,
                     dtype=cfg.dtype, name="tok_embed")(tokens)
        pos = nn.Embed(cfg.max_seq_len, cfg.embed_dim,
                       param_dtype=jnp.float32, dtype=cfg.dtype,
                       name="pos_embed")(jnp.arange(tokens.shape[1]))
        return x + pos[None]


class _LMHead(nn.Module):
    """Final layernorm + vocab projection (the post-pipeline epilogue)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return nn.Dense(self.cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=jnp.float32, use_bias=False,
                        name="head")(x)


class PipelinedTransformerLM:
    """Stacked-layer LM for pipeline parallelism (functional, not nn.Module).

    Every block parameter carries a leading ``layers`` dim sharded over the
    ``pipeline`` mesh axis; apply() routes the blocks through
    :func:`kubeflow_tpu.parallel.pipeline.pipeline_apply` (GPipe microbatch
    schedule over ICI ppermute) when the mesh has a pipeline axis, and a
    plain ``lax.scan`` over layers otherwise — same numerics either way.

    Reference parity: no analog (SURVEY.md §2.5 row 5 — the reference has
    no pipeline parallelism; this is the TPU-native capability add).
    """

    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg
        self.embed = _Embedder(cfg)
        self.block = Block(cfg)
        self.head = _LMHead(cfg)

    def init(self, rng: jax.Array, tokens: jax.Array) -> dict:
        r_embed, r_block, r_head = jax.random.split(rng, 3)
        ev = self.embed.init(r_embed, tokens)
        x = self.embed.apply(ev, tokens)
        block_rngs = jax.random.split(r_block, self.cfg.num_layers)
        p_blocks = jax.vmap(
            lambda r: self.block.init(r, x)["params"])(block_rngs)
        hv = self.head.init(r_head, x)
        return {"embed": ev["params"], "blocks": p_blocks,
                "head": hv["params"]}

    def apply(self, params: dict, tokens: jax.Array, *,
              mesh=None, num_microbatches: int = 1) -> jax.Array:
        from ..parallel.pipeline import pipeline_apply
        x = self.embed.apply({"params": params["embed"]}, tokens)

        def block_fn(p, h):
            return self.block.apply({"params": p}, h)

        if self.cfg.remat:
            block_fn = jax.checkpoint(block_fn)
        if mesh is not None and mesh.shape.get("pipeline", 1) > 1:
            x = pipeline_apply(block_fn, params["blocks"], x, mesh=mesh,
                               num_microbatches=num_microbatches)
        else:
            def body(h, p):
                return block_fn(p, h), None
            x, _ = jax.lax.scan(body, x, params["blocks"])
        return self.head.apply({"params": params["head"]}, x)


# Param-path → logical axes. Order matters: first match wins.
_LOGICAL_PATTERNS: list[tuple[str, tuple]] = [
    # "vocab_table", not "vocab": the table is GATHER-indexed on this
    # dim, and the DCN-aware rules replicate it on multi-slice meshes
    # (parallel/sharding_rules.py dcn_unsafe) — the head's matmul
    # "vocab" below stays tensor-sharded everywhere
    (r"tok_embed.*embedding", ("vocab_table", "embed")),
    (r"pos_embed.*embedding", (None, "embed")),
    (r"attn/qkv.*kernel", ("embed", None, "heads", "head_dim")),
    (r"attn/out.*kernel", ("heads", "head_dim", "embed")),
    (r"mlp/wi.*kernel", ("embed", "mlp")),
    (r"mlp/wo.*kernel", ("mlp", "embed")),
    (r"moe/router", ("embed", None)),
    (r"moe/wi", ("expert", "embed", "mlp")),
    (r"moe/wo", ("expert", "mlp", "embed")),
    (r"head.*kernel", ("embed", "vocab")),
    (r"(ln\d*|ln_f)/(scale|bias)", ("embed",)),
]


def logical_axes(params) -> Any:
    """Pytree (matching params) of logical-axis tuples, by path pattern."""

    def assign(path, leaf):
        path_str = "/".join(str(getattr(p, "key", p)) for p in path)
        for pat, axes in _LOGICAL_PATTERNS:
            if re.search(pat, path_str):
                assert len(axes) == leaf.ndim, \
                    f"{path_str}: {axes} vs shape {leaf.shape}"
                return axes
        return tuple([None] * leaf.ndim)

    return jax.tree_util.tree_map_with_path(assign, params)


def pipelined_logical_axes(params) -> Any:
    """Logical axes for the stacked PipelinedTransformerLM param tree:
    block leaves gain a leading "layers" axis (→ mesh axis "pipeline")."""

    def assign(path, leaf):
        path_str = "/".join(str(getattr(p, "key", p)) for p in path)
        stacked = path_str.startswith("blocks")
        for pat, axes in _LOGICAL_PATTERNS:
            if re.search(pat, path_str):
                if stacked:
                    axes = ("layers",) + axes
                assert len(axes) == leaf.ndim, \
                    f"{path_str}: {axes} vs shape {leaf.shape}"
                return axes
        base = tuple([None] * (leaf.ndim - (1 if stacked else 0)))
        return (("layers",) + base) if stacked else base

    return jax.tree_util.tree_map_with_path(assign, params)


def next_token_loss(logits: jax.Array, tokens: jax.Array) -> tuple:
    """Next-token loss with full-length input and shift-left targets.

    The input keeps length S (not S-1) so the sequence dim stays divisible
    by the "sequence" mesh axis under sequence parallelism; the final
    position is masked out of the loss instead.
    """
    targets = jnp.roll(tokens, -1, axis=1)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = jnp.ones_like(ll).at[:, -1].set(0.0)  # no target for last pos
    loss = -jnp.sum(ll * mask) / jnp.sum(mask)
    return loss, {"perplexity": jnp.exp(loss)}


def make_loss_fn(model: TransformerLM) -> Callable:
    moe = model.cfg.num_experts > 0

    def loss_fn(params, variables, batch, rng):
        tokens = batch["tokens"]
        if moe:
            from .moe import AUX_LOSS_COLLECTION
            logits, mods = model.apply({"params": params}, tokens,
                                       mutable=[AUX_LOSS_COLLECTION])
            loss, metrics = next_token_loss(logits, tokens)
            aux = sum(jax.tree.leaves(mods.get(AUX_LOSS_COLLECTION, {})),
                      jnp.float32(0))
            metrics["moe_aux_loss"] = aux
            return loss + aux, metrics
        logits = model.apply({"params": params}, tokens)
        return next_token_loss(logits, tokens)

    return loss_fn


def make_eval_fn(model: TransformerLM) -> Callable:
    """Held-out eval: next-token loss / perplexity / token accuracy (the
    LM analog of the image eval pass's top-1/top-5)."""

    def eval_fn(params, variables, batch):
        tokens = batch["tokens"]
        logits = model.apply({"params": params}, tokens)
        loss, _ = next_token_loss(logits, tokens)
        preds = jnp.argmax(logits[:, :-1], axis=-1)
        return {"eval_loss": loss,
                "eval_perplexity": jnp.exp(loss),
                "eval_token_accuracy": jnp.mean(preds == tokens[:, 1:])}

    return eval_fn


def init_fn(model: TransformerLM, seq_len: int, batch: int = 2) -> Callable:
    def _init(rng):
        variables = model.init(
            rng, jnp.zeros((batch, seq_len), jnp.int32))
        params = variables.pop("params")
        return params, dict(variables)

    return _init


def synthetic_batch(rng: jax.Array, batch_size: int, seq_len: int,
                    vocab_size: int) -> dict:
    return {"tokens": jax.random.randint(
        rng, (batch_size, seq_len), 0, vocab_size)}


def pipelined_workload_spec(cfg: Optional[TransformerConfig] = None,
                            seq_len: Optional[int] = None,
                            mesh=None, num_microbatches: int = 1):
    """WorkloadSpec for the stacked/pipelined LM (ShardingSpec.pipeline>1)."""
    from ..runtime.worker import WorkloadSpec
    cfg = cfg or TransformerConfig.tiny()
    if cfg.num_experts > 0:
        # the GPipe block scan never makes the "losses" collection mutable,
        # so MoE aux loss would silently vanish — refuse rather than train a
        # collapsed router
        raise NotImplementedError(
            "MoE (num_experts>0) is not supported on the pipelined path "
            "yet; use the non-pipelined transformer workload for EP")
    seq_len = seq_len or cfg.max_seq_len
    model = PipelinedTransformerLM(cfg)

    def _init(rng):
        return model.init(rng, jnp.zeros((2, seq_len), jnp.int32)), {}

    def loss_fn(params, variables, batch, rng):
        tokens = batch["tokens"]
        logits = model.apply(params, tokens, mesh=mesh,
                             num_microbatches=num_microbatches)
        return next_token_loss(logits, tokens)

    abstract = jax.eval_shape(lambda rng: _init(rng)[0], jax.random.PRNGKey(0))
    return WorkloadSpec(
        name="transformer-pipelined",
        init_fn=_init,
        loss_fn=loss_fn,
        batch_fn=lambda rng, bs: synthetic_batch(rng, bs, seq_len,
                                                 cfg.vocab_size),
        rules=TRANSFORMER_RULES,
        param_logical_axes=pipelined_logical_axes(abstract),
    )


def multislice_stage_fns(cfg: TransformerConfig) -> tuple:
    """The MPMD pipeline engine's stage contract
    (parallel/multislice.MPMDPipeline) for the pipelined LM:
    ``(init_fn, embed_fn, block_fn, head_loss_fn)``. ``init_fn`` is the
    FULL PipelinedTransformerLM init (same rng → bit-identical params to
    the single-program arm — the parity basis bench.py --mode multislice
    asserts); the per-stage fns reuse the exact modules the GPipe path
    applies, so stage math is the single-program math."""
    if cfg.num_experts > 0:
        raise NotImplementedError(
            "MoE is not supported on the MPMD multislice path yet "
            "(same limit as the single-program pipelined workload)")
    model = PipelinedTransformerLM(cfg)

    def init_fn(rng, seq_len=cfg.max_seq_len):
        return model.init(rng, jnp.zeros((2, seq_len), jnp.int32))

    def embed_fn(embed_params, tokens):
        return model.embed.apply({"params": embed_params}, tokens)

    def block_fn(layer_params, h):
        return model.block.apply({"params": layer_params}, h)

    def head_loss_fn(head_params, h, tokens):
        logits = model.head.apply({"params": head_params}, h)
        return next_token_loss(logits, tokens)

    return init_fn, embed_fn, block_fn, head_loss_fn


def workload_spec(cfg: Optional[TransformerConfig] = None,
                  seq_len: Optional[int] = None):
    """WorkloadSpec factory for runtime.worker (annotated for TP/SP/FSDP)."""
    from ..runtime.worker import WorkloadSpec
    cfg = cfg or TransformerConfig.tiny()
    seq_len = seq_len or cfg.max_seq_len
    model = TransformerLM(cfg)
    abstract = jax.eval_shape(
        lambda rng: init_fn(model, seq_len)(rng)[0], jax.random.PRNGKey(0))
    return WorkloadSpec(
        name="transformer",
        init_fn=init_fn(model, seq_len),
        loss_fn=make_loss_fn(model),
        batch_fn=lambda rng, bs: synthetic_batch(rng, bs, seq_len,
                                                 cfg.vocab_size),
        rules=TRANSFORMER_RULES,
        param_logical_axes=logical_axes(abstract),
        eval_fn=make_eval_fn(model),
    )
