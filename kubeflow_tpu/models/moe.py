"""Mixture-of-experts MLP with expert parallelism (EP).

The reference has no expert parallelism anywhere (SURVEY.md §2.5 row 5 /
§5 long-context note: the platform only scales data-parallel replicas); the
TPU build supplies EP natively as mesh-axis sharding. This is the GShard /
Switch-Transformer formulation expressed as einsums:

- a float32 router picks top-k experts per token under a capacity limit,
- dispatch/combine one-hot tensors route tokens to per-expert FFN weights
  that carry a leading logical "expert" axis (→ mesh axis "expert",
  parallel/sharding_rules.py),
- with tokens sharded over data axes and weights over the expert axis, XLA
  lowers the dispatch/combine einsums to ICI **all-to-all** collectives —
  the compiler-scheduled equivalent of the manual a2a in NCCL MoE stacks.

Capacity keeps shapes static (XLA requirement): each expert processes at
most C = ceil(top_k * S * capacity_factor / E) tokens per group; overflow
tokens are dropped (their combine weight is zero and the residual connection
carries them through unchanged), the standard TPU MoE trade.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

AUX_LOSS_COLLECTION = "losses"


def _top_k_mask(gates: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Per-token top-k expert assignment, returned one level at a time.
    Returns (indices [k, B, S], gate values [k, B, S])."""
    idxs, vals = [], []
    masked = gates
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)
        val = jnp.take_along_axis(masked, idx[..., None], axis=-1)[..., 0]
        idxs.append(idx)
        vals.append(val)
        masked = masked * (1.0 - jax.nn.one_hot(idx, gates.shape[-1],
                                                dtype=gates.dtype))
    return jnp.stack(idxs), jnp.stack(vals)


def load_balancing_loss(router_probs: jax.Array,
                        expert_index: jax.Array) -> jax.Array:
    """Switch-Transformer auxiliary loss: E * Σ_e f_e · P_e, minimized at
    uniform routing. f_e = fraction of tokens whose top-1 choice is e,
    P_e = mean router probability on e. All in float32."""
    num_experts = router_probs.shape[-1]
    f = jnp.mean(jax.nn.one_hot(expert_index, num_experts,
                                dtype=jnp.float32), axis=(0, 1))
    p = jnp.mean(router_probs.astype(jnp.float32), axis=(0, 1))
    return num_experts * jnp.sum(f * p)


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense MLP block.

    Attributes mirror TransformerConfig: ``num_experts``, ``top_k``,
    ``capacity_factor``, ``mlp_dim``, ``dtype``; aux loss is sown into the
    "losses" collection for the loss fn to pick up.
    """

    num_experts: int
    mlp_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        B, S, M = x.shape
        E, K = self.num_experts, self.top_k
        if not 1 <= K <= E:
            raise ValueError(f"top_k={K} must be in [1, num_experts={E}]")
        capacity = max(1, int(math.ceil(K * S * self.capacity_factor / E)))

        # router in float32: small matmul, numerically load-bearing
        router_kernel = self.param(
            "router", nn.initializers.lecun_normal(), (M, E), jnp.float32)
        router_logits = jnp.einsum("bsm,me->bse", x.astype(jnp.float32),
                                   router_kernel)
        router_probs = jax.nn.softmax(router_logits, axis=-1)

        expert_idx, expert_gate = _top_k_mask(router_probs, K)  # [K,B,S]

        aux = load_balancing_loss(router_probs, expert_idx[0])
        self.sow(AUX_LOSS_COLLECTION, "moe_aux",
                 self.aux_loss_weight * aux,
                 reduce_fn=lambda a, b: a + b, init_fn=lambda: jnp.float32(0))

        # capacity assignment: k-th choices queue behind all (k-1)-th
        # choices, GShard ordering; position = running count per expert
        onehots = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # [K,B,S,E]
        prev = jnp.zeros((B, 1, E), jnp.int32)
        dispatch_layers = []
        combine_gate_sum = jnp.zeros((B, S), jnp.float32)
        for k in range(K):
            oh = onehots[k]                                    # [B,S,E]
            pos = jnp.cumsum(oh, axis=1) - oh + prev           # [B,S,E]
            prev = prev + jnp.sum(oh, axis=1, keepdims=True)
            pos_tok = jnp.sum(pos * oh, axis=-1)               # [B,S]
            keep = (pos_tok < capacity).astype(jnp.float32)
            gate = expert_gate[k] * keep                       # [B,S]
            combine_gate_sum = combine_gate_sum + gate
            cap_oh = jax.nn.one_hot(pos_tok, capacity, dtype=jnp.float32)
            dispatch_layers.append(
                gate[..., None, None] * oh.astype(jnp.float32)[..., None]
                * cap_oh[:, :, None, :])                       # [B,S,E,C]
        combine = sum(dispatch_layers)                         # gated
        if K > 1:
            # renormalize so surviving gates sum to 1 per token; for K == 1
            # keep the raw router probability (Switch semantics) — a
            # renormalized top-1 gate is constant 1.0 and passes the router
            # zero gradient from the task loss
            denom = jnp.where(combine_gate_sum > 0, combine_gate_sum, 1.0)
            combine = combine / denom[..., None, None]
        dispatch = (combine > 0).astype(self.dtype)            # [B,S,E,C]
        combine = combine.astype(self.dtype)

        wi = self.param("wi", nn.initializers.lecun_normal(),
                        (E, M, self.mlp_dim), jnp.float32)
        wo = self.param("wo", nn.initializers.lecun_normal(),
                        (E, self.mlp_dim, M), jnp.float32)

        xd = x.astype(self.dtype)
        # all-to-all boundary: tokens regroup from data-sharding to
        # expert-sharding (XLA inserts the collective from the shardings)
        expert_in = jnp.einsum("bsec,bsm->ebcm", dispatch, xd)
        h = nn.gelu(jnp.einsum("ebcm,emh->ebch", expert_in,
                               wi.astype(self.dtype)))
        expert_out = jnp.einsum("ebch,ehm->ebcm", h, wo.astype(self.dtype))
        # all-to-all back: expert-sharding → data-sharding
        return jnp.einsum("bsec,ebcm->bsm", combine, expert_out)
