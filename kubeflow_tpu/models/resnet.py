"""ResNet-50 in flax, TPU-first.

The flagship benchmark workload: the TPU-native counterpart of the
reference's tf_cnn_benchmarks ResNet-50 TFJob
(tf-controller-examples/tf-cnn/launcher.py runs tf_cnn_benchmarks with
variable_update=parameter_server; here the same model trains data-parallel
over ICI via one pjit step).

TPU design notes:
- bfloat16 compute / float32 params and batch stats: convs hit the MXU at
  full rate in bf16.
- NHWC layout (XLA:TPU's native conv layout).
- BatchNorm stats folded into the jitted step via the flax mutable-variables
  path; cross-replica stat sync uses the batch axis only at eval export.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any

from . import RESNET_DEPTHS  # noqa: F401 — canonical family tuple

STAGE_SIZES = {
    18: [2, 2, 2, 2],
    34: [3, 4, 6, 3],
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
}
assert set(STAGE_SIZES) == set(RESNET_DEPTHS), \
    "models.RESNET_DEPTHS out of sync with resnet.STAGE_SIZES"


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 strides=(self.strides, self.strides),
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 strides=(self.strides, self.strides),
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    num_classes: int = 1000
    depth: int = 50
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)
        block = BottleneckBlock if self.depth >= 50 else BasicBlock

        x = x.astype(self.dtype)
        x = conv(self.width, (7, 7), strides=(2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(STAGE_SIZES[self.depth]):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = block(self.width * 2 ** i, strides, conv, norm,
                          name=f"stage{i + 1}_block{j + 1}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def make_resnet(depth: int, num_classes: int = 1000, **kw) -> ResNet:
    """The tf_cnn_benchmarks --model family: resnet{18,34,50,101,152}
    (BasicBlock below depth 50, bottleneck at and above)."""
    if depth not in STAGE_SIZES:
        raise ValueError(f"unsupported ResNet depth {depth}; "
                         f"one of {sorted(STAGE_SIZES)}")
    return ResNet(num_classes=num_classes, depth=depth, **kw)


def resnet18(num_classes: int = 1000, **kw) -> ResNet:
    return make_resnet(18, num_classes, **kw)


def resnet34(num_classes: int = 1000, **kw) -> ResNet:
    return make_resnet(34, num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return make_resnet(50, num_classes, **kw)


def resnet101(num_classes: int = 1000, **kw) -> ResNet:
    return make_resnet(101, num_classes, **kw)


def resnet152(num_classes: int = 1000, **kw) -> ResNet:
    return make_resnet(152, num_classes, **kw)


def per_row_cross_entropy(logits: jax.Array, labels: jax.Array,
                          label_smoothing: float = 0.0) -> jax.Array:
    onehot = jax.nn.one_hot(labels, logits.shape[-1])
    if label_smoothing:
        # the tf_cnn_benchmarks/ResNet recipe regularizer (0.1 for the
        # 76%-top-1 ImageNet run)
        n = logits.shape[-1]
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / n
    return -jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       label_smoothing: float = 0.0) -> jax.Array:
    return jnp.mean(per_row_cross_entropy(logits, labels, label_smoothing))


def make_loss_fn(model: ResNet, label_smoothing: float = 0.0) -> Callable:
    """Loss fn in the TrainStepBuilder signature; threads batch_stats."""

    def loss_fn(params, variables, batch, rng):
        images, labels = batch["images"], batch["labels"]
        logits, updated = model.apply(
            {"params": params, **variables}, images, train=True,
            mutable=["batch_stats"])
        loss = cross_entropy_loss(logits, labels, label_smoothing)
        acc = jnp.mean(jnp.argmax(logits, -1) == labels)
        return loss, {"accuracy": acc, "variables": updated}

    return loss_fn


def make_eval_fn(model: ResNet) -> Callable:
    """Eval pass: running-stats forward (train=False), top-1/top-5 — the
    metrics the ImageNet acceptance target is stated in.

    An optional ``batch["weight"]`` (float (B,), 0/1) masks rows out of
    every metric: the worker pads the holdout's final partial batch to
    the compiled batch shape and zero-weights the padding, so a full
    eval pass counts every record exactly once."""

    def eval_fn(params, variables, batch):
        images, labels = batch["images"], batch["labels"]
        logits = model.apply({"params": params, **variables}, images,
                             train=False)
        w = batch.get("weight")
        if w is None:
            w = jnp.ones((labels.shape[0],), jnp.float32)
        denom = jnp.maximum(jnp.sum(w), 1.0)
        loss = jnp.sum(per_row_cross_entropy(logits, labels) * w) / denom
        top1 = jnp.sum((jnp.argmax(logits, -1) == labels) * w) / denom
        _, top5_idx = jax.lax.top_k(logits, 5)
        top5 = jnp.sum(
            jnp.any(top5_idx == labels[:, None], axis=-1) * w) / denom
        return {"eval_loss": loss, "top1": top1, "top5": top5}

    return eval_fn


def init_fn(model: ResNet, image_size: int = 224, batch: int = 8) -> Callable:
    def _init(rng):
        variables = model.init(
            rng, jnp.zeros((batch, image_size, image_size, 3), jnp.float32),
            train=False)
        params = variables.pop("params")
        return params, dict(variables)

    return _init


def synthetic_batch(rng: jax.Array, batch_size: int, image_size: int = 224,
                    num_classes: int = 1000) -> dict:
    """Synthetic ImageNet-shaped data (the tf_cnn_benchmarks --data_name
    synthetic mode the CI config used)."""
    k1, k2 = jax.random.split(rng)
    return {
        "images": jax.random.normal(
            k1, (batch_size, image_size, image_size, 3), jnp.float32),
        "labels": jax.random.randint(k2, (batch_size,), 0, num_classes),
    }


# -- fused inference path (ops/fused_block.py) -------------------------------

def _affine(bn_params, bn_stats, eps=1e-5):
    from ..ops.fused_block import _fold_bn  # one folding formula, one place
    return _fold_bn(bn_params, bn_stats, eps)


def _xla_block_eval(x, params, stats, strides, dtype=jnp.bfloat16):
    """Strided bottleneck block via lax convs with folded BN (the blocks
    the fused kernel does not cover)."""
    from jax import lax

    def conv(h, kernel, stride):
        return lax.conv_general_dilated(
            h, kernel.astype(dtype), (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def bn_relu(h, name, relu=True):
        s, b = _affine(params[name], stats[name])
        h = h.astype(jnp.float32) * s + b
        if relu:
            h = jax.nn.relu(h)
        return h.astype(dtype)

    y = bn_relu(conv(x, params["Conv_0"]["kernel"], 1), "BatchNorm_0")
    y = bn_relu(conv(y, params["Conv_1"]["kernel"], strides), "BatchNorm_1")
    y = bn_relu(conv(y, params["Conv_2"]["kernel"], 1), "BatchNorm_2",
                relu=False)
    if "conv_proj" in params:
        res = bn_relu(conv(x, params["conv_proj"]["kernel"], strides),
                      "norm_proj", relu=False)
    else:
        res = x
    return jax.nn.relu(res.astype(jnp.float32) +
                       y.astype(jnp.float32)).astype(dtype)


def fused_eval_apply(variables: dict, images: jax.Array, *,
                     depth: int = 50,
                     dtype=jnp.bfloat16, block_bt=None) -> jax.Array:
    """Inference forward with every stride-1 bottleneck running as ONE
    Pallas kernel (ops/fused_block.py). Numerically the same computation
    as ``model.apply(..., train=False)`` (BN running stats fold to exact
    affines) — but MEASURED SLOWER than the standard XLA eval path
    (6.8k vs 11.5k img/s at 224px/bs128, PERF.md): XLA already fuses the
    folded affines into conv epilogues at inference. Kept as the tested
    baseline for the training-mode fused kernel, NOT the serving default.
    Bottleneck depths only (>= 50)."""
    if depth < 50:
        raise ValueError("fused_eval_apply supports bottleneck depths "
                         "(>= 50); BasicBlock models have no Conv_2")
    from jax import lax

    from ..ops.fused_block import fold_block, fused_bottleneck_eval

    params, stats = variables["params"], variables["batch_stats"]
    x = images.astype(dtype)
    x = lax.conv_general_dilated(
        x, params["conv_init"]["kernel"].astype(dtype), (2, 2), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    s, b = _affine(params["bn_init"], stats["bn_init"])
    x = jax.nn.relu(x.astype(jnp.float32) * s + b).astype(dtype)
    x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

    for i, n_blocks in enumerate(STAGE_SIZES[depth]):
        for j in range(n_blocks):
            name = f"stage{i + 1}_block{j + 1}"
            strides = 2 if i > 0 and j == 0 else 1
            if strides == 1:
                w = fold_block(params[name], stats[name])
                x = fused_bottleneck_eval(x, w, block_bt=block_bt)
            else:
                x = _xla_block_eval(x, params[name], stats[name], strides,
                                    dtype=dtype)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    head = params["head"]
    return x @ head["kernel"].astype(jnp.float32) + head["bias"]


# -- fused ghost-BN training path (ops/fused_block_train.py) ------------------

_BN_MOMENTUM = 0.9  # must match the norm partial in ResNet.__call__


def _bn_train(a, scale, bias, eps=1e-5):
    """Train-mode BatchNorm over the full (local) batch in plain jnp —
    differentiable, for the blocks the fused kernel does not cover.
    Returns (y, batch_mean, batch_var)."""
    f32 = jnp.float32
    af = a.astype(f32)
    m = jnp.mean(af, axis=(0, 1, 2))
    v = jnp.mean(jnp.square(af), axis=(0, 1, 2)) - jnp.square(m)
    xh = (af - m) * jax.lax.rsqrt(v + eps)
    return (scale * xh + bias).astype(a.dtype), m, v


def _xla_block_train(x, params, strides, dtype=jnp.bfloat16, eps=1e-5):
    """Strided bottleneck block, train mode, via lax convs + _bn_train
    (the fused kernel covers stride-1 blocks only). Returns
    (out, batch-moment subtree)."""
    from jax import lax

    def conv(h, kernel, stride):
        return lax.conv_general_dilated(
            h, kernel.astype(dtype), (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    stats = {}

    def bn(h, name, relu=True):
        y, m, v = _bn_train(h, params[name]["scale"], params[name]["bias"],
                            eps)
        stats[name] = {"mean": m, "var": v}
        return jax.nn.relu(y) if relu else y

    y = bn(conv(x, params["Conv_0"]["kernel"], 1), "BatchNorm_0")
    y = bn(conv(y, params["Conv_1"]["kernel"], strides), "BatchNorm_1")
    y = bn(conv(y, params["Conv_2"]["kernel"], 1), "BatchNorm_2",
           relu=False)
    if "conv_proj" in params:
        res = bn(conv(x, params["conv_proj"]["kernel"], strides),
                 "norm_proj", relu=False)
    else:
        res = x
    out = jax.nn.relu(res.astype(jnp.float32) +
                      y.astype(jnp.float32)).astype(dtype)
    return out, stats


def geometry_key(h: int, w: int, cin: int, cmid: int, cout: int) -> str:
    """Stable key for one bottleneck geometry — the lookup key of the
    measured routing table (KFTPU_FUSED_ROUTING_TABLE)."""
    return f"{h}x{w}_{cin}_{cmid}_{cout}"


def _measured_routing_table() -> dict | None:
    """Measured per-geometry kernel routing, loaded once per process from
    the JSON file named by KFTPU_FUSED_ROUTING_TABLE (written by
    ``bench.py --mode fused-blocks`` on real TPU): geometry_key →
    "xla" | "batch" | "spatial:<tile_h>". Measured beats modeled — the
    round-5 silicon session showed the VMEM traffic model mispredicts
    which kernels win (PERF.md), so routing can be pinned to what the
    chip actually measured."""
    import json
    import os
    path = os.environ.get("KFTPU_FUSED_ROUTING_TABLE")
    if not path:
        return None
    cached = _measured_routing_table.__dict__.get("cache")
    if cached is not None and cached[0] == path:
        return cached[1]
    try:
        with open(path) as f:
            table = json.load(f)
    except (OSError, ValueError) as e:
        # a misconfigured ConfigMap mount (wrong mountPath, truncated or
        # non-JSON data) must fail naming the knob and the path — a bare
        # FileNotFoundError from deep inside the loss build is
        # undiagnosable from a pod log (ADVICE.md)
        raise RuntimeError(
            f"KFTPU_FUSED_ROUTING_TABLE={path!r}: cannot load measured "
            f"routing table ({type(e).__name__}: {e}); fix or unset the "
            "env var / ConfigMap mount (manifests/training.py "
            "tpu_job_simple fused_routing)") from e
    routes = table.get("routes", table)   # accept bare or wrapped
    _measured_routing_table.cache = (path, routes)
    return routes


def _fused_route(h: int, w: int, cin: int, cmid: int,
                 cout: int) -> tuple:
    """Kernel choice for one stride-1 bottleneck: ("batch", None) when
    one image's working set fits VMEM, ("spatial", tile_h) when a halo
    strip does, ("xla", None) otherwise. The single source of truth for
    fused_train_apply AND the bench artifact's routing report.

    A measured table (KFTPU_FUSED_ROUTING_TABLE) overrides the model
    for the geometries it names. KFTPU_FUSED_DISABLE_SPATIAL=1 turns
    the spatial branch off (blocks that don't batch-tile fall to XLA)
    — the kill-switch for a Mosaic compile of the spatial kernels
    going bad mid-measurement (hack/tpu_session.sh retries the fused
    bench with it set)."""
    import os

    from ..ops.fused_block_train import fits_vmem_budget
    from ..ops.fused_block_train_spatial import default_tile_h

    spatial_disabled = os.environ.get(
        "KFTPU_FUSED_DISABLE_SPATIAL", "").lower() in ("1", "true", "yes")
    table = _measured_routing_table()
    if table is not None:
        route = table.get(geometry_key(h, w, cin, cmid, cout))
        if route == "xla":
            return ("xla", None)
        if route == "batch":
            return ("batch", None)
        if isinstance(route, str) and route.startswith("spatial:"):
            # the kill-switch outranks the table: a wedged spatial
            # Mosaic compile must be stoppable even with routes pinned
            return ("xla", None) if spatial_disabled else \
                ("spatial", int(route.split(":", 1)[1]))
    if fits_vmem_budget(h, w, cin, cmid, cout):
        return ("batch", None)
    if spatial_disabled:
        return ("xla", None)
    th = default_tile_h(h, w, cin, cmid, cout)
    return ("spatial", th) if th is not None else ("xla", None)


def _block_walk(depth: int, image_size: int):
    """Yield every bottleneck block's geometry in model order — the ONE
    copy of the SAME-padding ceil-division recurrence (conv_init s2 +
    maxpool s2, then 64·2^stage widths, stride 2 at each later stage
    head): {name, h, cin, cmid, cout, strides}. fused_block_routing,
    stride1_geometries, and (transitively) the bench artifact all read
    this walk, so they cannot drift from each other; pinned against the
    apply's real tensor shapes in tests/test_ops.py."""
    if depth < 50:
        raise ValueError("fused paths cover bottleneck depths (>= 50)")

    def ceil_half(n: int) -> int:     # SAME conv/pool, stride 2
        return -(-n // 2)

    h = ceil_half(ceil_half(image_size))   # conv_init s2 + maxpool s2
    cin = 64
    for i, n_blocks in enumerate(STAGE_SIZES[depth]):
        cmid = 64 * 2 ** i
        cout = cmid * 4
        for j in range(n_blocks):
            strides = 2 if i > 0 and j == 0 else 1
            if strides == 2:
                h = ceil_half(h)
            yield {"name": f"stage{i + 1}_block{j + 1}", "h": h,
                   "cin": cin, "cmid": cmid, "cout": cout,
                   "strides": strides}
            cin = cout


def fused_block_routing(depth: int = 50,
                        image_size: int = 224) -> dict[str, str]:
    """block name → kernel route for the fused training path: the same
    decision function the apply executes (_fused_route), over the same
    geometry (_block_walk) — what `bench.py` records so the artifact
    says what actually ran."""
    routes = {}
    for b in _block_walk(depth, image_size):
        if b["strides"] != 1:
            routes[b["name"]] = "xla-strided"
        else:
            kind, th = _fused_route(b["h"], b["h"], b["cin"], b["cmid"],
                                    b["cout"])
            routes[b["name"]] = {"batch": "fused-batch",
                                 "xla": "xla"}.get(
                kind, f"fused-spatial(th={th})")
    return routes


def stride1_geometries(depth: int = 50,
                       image_size: int = 224) -> list[dict]:
    """The distinct stride-1 bottleneck geometries of one model config,
    with multiplicity — the work-list for the per-block kernel
    microbench (``bench.py --mode fused-blocks``). Aggregates
    _block_walk (the single geometry recurrence); each entry carries
    {key, h, cin, cmid, cout, proj, count}."""
    geoms: dict[str, dict] = {}
    for b in _block_walk(depth, image_size):
        if b["strides"] != 1:
            continue
        key = geometry_key(b["h"], b["h"], b["cin"], b["cmid"], b["cout"])
        g = geoms.setdefault(key, {
            "key": key, "h": b["h"], "cin": b["cin"], "cmid": b["cmid"],
            "cout": b["cout"], "proj": b["cin"] != b["cout"], "count": 0})
        g["count"] += 1
    return list(geoms.values())


def random_block_params(rng: jax.Array, cin: int, cmid: int, cout: int,
                        proj: bool) -> dict:
    """He-init params for ONE bottleneck block at an arbitrary geometry
    (the microbench's model-free block constructor; same subtree shape
    the flax model produces)."""
    import flax.linen as fnn
    ks = jax.random.split(rng, 4)
    init = fnn.initializers.he_normal()

    def bn(c):
        return {"scale": jnp.ones((c,), jnp.float32),
                "bias": jnp.zeros((c,), jnp.float32)}

    p = {"Conv_0": {"kernel": init(ks[0], (1, 1, cin, cmid), jnp.float32)},
         "BatchNorm_0": bn(cmid),
         "Conv_1": {"kernel": init(ks[1], (3, 3, cmid, cmid), jnp.float32)},
         "BatchNorm_1": bn(cmid),
         "Conv_2": {"kernel": init(ks[2], (1, 1, cmid, cout), jnp.float32)},
         "BatchNorm_2": bn(cout)}
    if proj:
        p["conv_proj"] = {
            "kernel": init(ks[3], (1, 1, cin, cout), jnp.float32)}
        p["norm_proj"] = bn(cout)
    return p


def fused_train_apply(variables: dict, images: jax.Array, *,
                      depth: int = 50, tile_bt=None,
                      dtype=jnp.bfloat16, eps: float = 1e-5,
                      pmean_axes: tuple = ()) -> tuple[jax.Array, dict]:
    """Training forward with every stride-1 bottleneck running as ONE
    fused ghost-BN Pallas kernel (ops/fused_block_train.py) under
    custom_vjp — the opt-in variant that cuts the HBM traffic the
    step is roofline-bound on (PERF.md).

    Ghost semantics: BN statistics are per kernel batch-tile (and per
    data-parallel shard when called inside shard_map); running stats are
    EMA-updated from the tile-averaged moments, pmean'd over
    ``pmean_axes`` when set. Returns (logits, new_batch_stats)."""
    if depth < 50:
        raise ValueError("fused_train_apply supports bottleneck depths "
                         "(>= 50); BasicBlock models have no Conv_2")
    from jax import lax

    from ..ops.fused_block_train import fused_bottleneck_train
    from ..ops.fused_block_train_spatial import (
        fused_bottleneck_train_spatial)

    params, stats = variables["params"], variables["batch_stats"]
    batch_moments: dict = {}
    x = images.astype(dtype)
    x = lax.conv_general_dilated(
        x, params["conv_init"]["kernel"].astype(dtype), (2, 2), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y, m, v = _bn_train(x, params["bn_init"]["scale"],
                        params["bn_init"]["bias"], eps)
    batch_moments["bn_init"] = {"mean": m, "var": v}
    x = jax.nn.relu(y)
    x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

    for i, n_blocks in enumerate(STAGE_SIZES[depth]):
        for j in range(n_blocks):
            name = f"stage{i + 1}_block{j + 1}"
            strides = 2 if i > 0 and j == 0 else 1
            bp = params[name]
            _, h, w_, cin = x.shape
            cmid = bp["Conv_0"]["kernel"].shape[-1]
            cout = bp["Conv_2"]["kernel"].shape[-1]
            # strided blocks the kernels don't cover route to XLA;
            # stride-1 blocks batch-tile when one image fits VMEM and
            # fall back to the spatially-tiled (halo) kernel for the
            # large early-stage geometries, XLA as the last resort
            # (_fused_route is shared with fused_block_routing so the
            # bench artifact reports exactly this decision)
            kind, th = ("xla", None) if strides != 1 else \
                _fused_route(h, w_, cin, cmid, cout)
            if kind == "batch":
                x, bstats = fused_bottleneck_train(x, bp, tile_bt=tile_bt,
                                                   eps=eps)
            elif kind == "spatial":
                x, bstats = fused_bottleneck_train_spatial(x, bp,
                                                           tile_h=th,
                                                           eps=eps)
            else:
                x, bstats = _xla_block_train(x, bp, strides,
                                             dtype=dtype, eps=eps)
            batch_moments[name] = bstats

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    head = params["head"]
    logits = x @ head["kernel"].astype(jnp.float32) + head["bias"]

    if pmean_axes:
        batch_moments = jax.lax.pmean(batch_moments, pmean_axes)
    # running-stat EMA, flax semantics: ra = m·ra + (1−m)·batch
    new_stats = jax.tree.map(
        lambda ra, b: _BN_MOMENTUM * ra + (1.0 - _BN_MOMENTUM)
        * jax.lax.stop_gradient(b), stats, batch_moments)
    return logits, new_stats


def make_fused_loss_fn(model: ResNet, label_smoothing: float = 0.0,
                       tile_bt=None, mesh=None) -> Callable:
    """Loss fn (TrainStepBuilder signature) over fused_train_apply.

    On a mesh with >1 device on the data axes the apply runs inside
    jax.shard_map over those axes: GSPMD cannot partition an opaque
    pallas_call, and per-shard ghost BN is exactly the per-replica BN
    semantics data-parallel trainers ship with. Weight gradients are
    psummed by the shard_map transpose (replicated in_spec); batch
    moments are pmean'd explicitly before the EMA."""
    depth = model.depth
    if depth < 50:
        raise ValueError("fused blocks require a bottleneck ResNet "
                         "(depth >= 50)")

    def apply_fn(variables, images):
        return fused_train_apply(variables, images, depth=depth,
                                 tile_bt=tile_bt, dtype=model.dtype)

    run = apply_fn
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import data_axes
        axes = data_axes(mesh)
        dp = 1
        for a in axes:
            dp *= mesh.shape[a]
        if dp > 1:
            def sharded(variables, images):
                return fused_train_apply(variables, images, depth=depth,
                                         tile_bt=tile_bt,
                                         dtype=model.dtype,
                                         pmean_axes=axes)

            from ..parallel.compat import shard_map
            run = shard_map(
                sharded, mesh=mesh, in_specs=(P(), P(axes)),
                out_specs=(P(axes), P()), check_vma=False)

    def loss_fn(params, variables, batch, rng):
        logits, new_stats = run({"params": params, **variables},
                                batch["images"])
        labels = batch["labels"]
        loss = cross_entropy_loss(logits, labels, label_smoothing)
        acc = jnp.mean(jnp.argmax(logits, -1) == labels)
        return loss, {"accuracy": acc,
                      "variables": {"batch_stats": new_stats}}

    return loss_fn
