"""Goodput ledger: per-job wall-clock accounting from queue to chip.

Of a job's wall-clock chip-hours, how many trained the model — and where
did the rest go? Every prior layer already emits the raw evidence
(queue/bind/preempt/resize spans from the scheduler, restart and stall
transitions from the operator, first-step/window/checkpoint spans from
the worker); this module folds that one span stream into the operator's
first dashboard: **goodput** (productive train steps) vs named **badput**
categories. The decomposition vocabulary is how scheduler-policy papers
actually compare arms ("Dynamic Scheduling of MPI-based Distributed Deep
Learning Training Jobs" evaluates entirely in queue-wait/utilization
decompositions; TF-Replicator motivates per-step breakdowns as the first
debugging surface — PAPERS.md), so the sim (scheduler/sim.py) reports
the SAME categories and an arm's table is comparable to a real cluster's.

The category vocabulary is defined ONCE, here, and consumed by the
ledger, the sim, the dashboard, and the operator's final-ledger export —
tests/test_lint.py pins the single definition (the binding_of rule).

Accounting model: the ledger partitions the job's wall interval
[first span start, last span end] — every elementary interval between
span boundaries is attributed to exactly ONE category by priority, so
the categories sum to wall-clock BY CONSTRUCTION (the bench's 2%
tolerance covers boundary fuzz between independently-clocked writers,
not accounting leaks). Time nothing claims is reported honestly as
``other``, never silently absorbed into goodput.

jax-free, stdlib only — the scheduler, operator, and dashboard all
import this.
"""

from __future__ import annotations

import json
import math
from typing import Optional

from . import registry as obsreg
from .trace import load_spans

# ---------------------------------------------------------- the vocabulary
# Badput category names — the ONE definition (ledger, sim, dashboard, and
# bench all import these; tests/test_lint.py greps that the literals
# appear nowhere else in the package).
GOODPUT = "goodput"
BADPUT_QUEUE_WAIT = "queue_wait"          # admission → slice binding
BADPUT_STARTUP = "startup"                # bind → worker first activity
#                                           (pod create, image, backend)
BADPUT_COMPILE = "compile"                # train() entry → first step,
#                                           split cold/warm/aot
BADPUT_CHECKPOINT = "checkpoint"          # save submission + restore
BADPUT_RECOMPUTE = "restart_recompute"    # steps re-executed after resume
BADPUT_ROLLBACK = "rollback_recompute"    # steps replayed LKG → trip after
#                                           an anomaly rollback (the
#                                           sentinel's recovery cost —
#                                           split out of restart_recompute
#                                           so SDC waste is its own line)
BADPUT_RESIZE = "resize"                  # resize/migration downtime
BADPUT_STALL = "stall"                    # wedged → watchdog teardown
BADPUT_PIPELINE_BUBBLE = "pipeline_bubble"  # MPMD pipeline fill/drain
#                                           idle (parallel/multislice.py
#                                           schedule model; the worker
#                                           emits per-window
#                                           pipeline-bubble spans)
BADPUT_OTHER = "other"                    # unattributed residual

BADPUT_CATEGORIES = (BADPUT_QUEUE_WAIT, BADPUT_STARTUP, BADPUT_COMPILE,
                     BADPUT_CHECKPOINT, BADPUT_RECOMPUTE, BADPUT_ROLLBACK,
                     BADPUT_RESIZE, BADPUT_STALL, BADPUT_PIPELINE_BUBBLE,
                     BADPUT_OTHER)

# the operator stamps a job's final ledger here on completion
# (controllers/tpujob.py _finalize_ledger) so the decomposition survives
# span-sink rotation/GC
GOODPUT_ANNOTATION = "observability.kubeflow.org/goodput"

# ------------------------------------------- the SERVING request vocabulary
# The same accounting discipline applied to the request path: of one
# request's wall-clock, how much was the device doing real work — and
# where did the rest go? Defined ONCE here (the training-vocabulary
# rule above); the request tracer (serving/request_trace.py), the
# replica registry (serving/replica_state.py), the dashboard's
# /api/obs/serving rollup, and the bench all import these.
# Device time on REAL rows is serving goodput; the pad fraction of the
# same device interval is `pad_waste` — a full batch has zero.
SERVING_QUEUE = "queue"                 # accept → pulled into a batch
SERVING_BATCH_FORM = "batch_form"       # cohort grouping + concat + pad
SERVING_PAD_WASTE = "pad_waste"         # device time spent on pad rows
SERVING_H2D = "h2d"                     # host → device transfer
SERVING_DEVICE = "device"               # device compute (real-row share
#                                         reported as goodput)
SERVING_RESPOND = "respond"             # drain + fan-out + serialization

SERVING_BADPUT_CATEGORIES = (SERVING_QUEUE, SERVING_BATCH_FORM,
                             SERVING_PAD_WASTE, SERVING_H2D,
                             SERVING_RESPOND, BADPUT_OTHER)

# the one summary span every request emits (stage spans are sampled;
# the ledger always lands) — serving_rollup() and the dashboard read it
SERVING_REQUEST_SPAN = "serving-request"
# stage spans a sampled request emits, in request order
SERVING_STAGE_SPANS = ("accept", "queue", "batch-form", "h2d", "device",
                       "drain", "respond")

# --------------------------------------------- the FLEET request vocabulary
# The fleet router (serving/fleet.py) applies the same accounting
# discipline one layer up: of one ROUTED request's client wall-clock,
# how much was the winning upstream attempt — and where did the rest
# go? Failed attempts and their backoff sleeps are `retry` badput; a
# lost tail-hedge's duplicated upstream work is `hedge_waste`. Defined
# ONCE here (the single-definition rule above); the fleet router, the
# dashboard's /api/obs/fleet rollup, and the bench all import these.
SERVING_RETRY = "retry"                 # failed attempts + backoff sleeps
SERVING_HEDGE_WASTE = "hedge_waste"     # lost-hedge duplicated upstream work

FLEET_BADPUT_CATEGORIES = (SERVING_RETRY, SERVING_HEDGE_WASTE,
                           BADPUT_OTHER)

# the one summary span the fleet router emits per routed request
FLEET_REQUEST_SPAN = "fleet-request"
# fleet event spans (retry/hedge/breaker/drain transitions), stamped
# with the request id where one applies
FLEET_EVENT_SPANS = ("fleet-retry", "fleet-hedge", "fleet-eject",
                     "fleet-admit", "fleet-drain")


def decompose_fleet_request(wall_seconds: float, upstream_seconds: float,
                            retry_seconds: float,
                            hedge_waste_seconds: float = 0.0) -> dict:
    """Fold one routed request's measured attempt seconds into its
    fleet ledger. The client wall-clock partitions as upstream (the
    winning attempt) + retry (failed attempts and backoff sleeps,
    sequential on the wall) + other (client-side routing overhead —
    reported honestly, never absorbed). ``hedge_waste`` is the lost
    hedge's duplicated upstream work: it OVERLAPS the winner on the
    wall, so it is named badput (chip time wasted) outside the wall
    partition — ``fleet_sum_ok`` checks upstream + retry + other
    against wallSeconds and deliberately excludes it."""
    wall = max(0.0, float(wall_seconds))
    upstream = max(0.0, float(upstream_seconds))
    retry = max(0.0, float(retry_seconds))
    other = max(0.0, wall - upstream - retry)
    return {
        "wallSeconds": round(wall, 6),
        "upstreamSeconds": round(upstream, 6),
        "upstreamRatio": round(upstream / wall, 6) if wall else 0.0,
        "badputSeconds": {
            SERVING_RETRY: round(retry, 6),
            SERVING_HEDGE_WASTE: round(
                max(0.0, float(hedge_waste_seconds)), 6),
            BADPUT_OTHER: round(other, 6),
        },
    }


def fleet_sum_ok(ledger: dict, tol: float = 0.02) -> bool:
    """Whether a fleet ledger's wall partition holds: upstream + retry
    + other re-adds to wallSeconds within ``tol`` (hedge_waste overlaps
    the winner and is excluded by contract — see
    decompose_fleet_request)."""
    wall = float(ledger.get("wallSeconds", 0.0))
    bad = ledger.get("badputSeconds") or {}
    total = float(ledger.get("upstreamSeconds", 0.0)) + \
        float(bad.get(SERVING_RETRY, 0.0)) + \
        float(bad.get(BADPUT_OTHER, 0.0))
    return abs(total - wall) <= max(tol * wall, 1e-6)


def fleet_rollup(path: str) -> dict:
    """The fleet rollup off the span sink: every ``fleet-request``
    summary span folded into one table — request/outcome counts,
    attempt/retry/hedge totals, p50/p99/p99.9 client latency, summed
    fleet badput, and per-replica win counts. jax-free; the dashboard
    serves this at /api/obs/fleet."""
    lat: list = []
    outcomes: dict = {}
    per_replica: dict = {}
    bad = {c: 0.0 for c in FLEET_BADPUT_CATEGORIES}
    wall_s = upstream_s = 0.0
    attempts = retries = hedges = 0
    for rec in load_spans(path):
        if rec.get("name") != FLEET_REQUEST_SPAN:
            continue
        a = _attrs(rec)
        ledger = a.get("ledger")
        ledger = ledger if isinstance(ledger, dict) else {}
        wall = float(ledger.get("wallSeconds", 0.0) or 0.0)
        lat.append(wall)
        wall_s += wall
        upstream_s += float(ledger.get("upstreamSeconds", 0.0) or 0.0)
        for c, v in (ledger.get("badputSeconds") or {}).items():
            if c in bad:
                bad[c] += float(v or 0.0)
        outcome = str(a.get("outcome", "ok"))
        outcomes[outcome] = outcomes.get(outcome, 0) + 1
        attempts += int(a.get("attempts", 1) or 1)
        retries += int(a.get("retries", 0) or 0)
        if a.get("hedged"):
            hedges += 1
        replica = str(a.get("replica", ""))
        if replica:
            per_replica[replica] = per_replica.get(replica, 0) + 1
    lat.sort()
    n = len(lat)
    return {
        "requests": n,
        "outcomes": outcomes,
        "attempts": attempts,
        "retries": retries,
        "hedged": hedges,
        "p50Ms": round(_percentile(lat, 0.50) * 1e3, 3),
        "p99Ms": round(_percentile(lat, 0.99) * 1e3, 3),
        "p999Ms": round(_percentile(lat, 0.999) * 1e3, 3),
        "upstreamRatio": round(upstream_s / wall_s, 6) if wall_s else 0.0,
        "badputSeconds": {c: round(v, 6) for c, v in bad.items()},
        "replicas": dict(sorted(per_replica.items())),
    }


def decompose_request(wall_seconds: float, stages: dict) -> dict:
    """Fold one request's measured stage seconds into its ledger —
    the request-path analog of decompose(). ``stages`` maps category
    names (plus SERVING_DEVICE for the real-work device share) to
    seconds; the residual nothing claims is reported as ``other``,
    never absorbed (the training-ledger rule). Categories plus goodput
    sum to wallSeconds exactly whenever the stages fit inside the wall
    (clock fuzz between threads is what the bench's 2% covers)."""
    wall = max(0.0, float(wall_seconds))
    goodput = max(0.0, float(stages.get(SERVING_DEVICE, 0.0)))
    bad = {c: max(0.0, float(stages.get(c, 0.0)))
           for c in SERVING_BADPUT_CATEGORIES if c != BADPUT_OTHER}
    total = goodput + sum(bad.values())
    bad[BADPUT_OTHER] = max(0.0, wall - total)
    return {
        "wallSeconds": round(wall, 6),
        "goodputSeconds": round(goodput, 6),
        "goodputRatio": round(goodput / wall, 6) if wall else 0.0,
        "badputSeconds": {c: round(bad[c], 6)
                          for c in SERVING_BADPUT_CATEGORIES},
    }


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(len(sorted_vals) * q))]


def serving_rollup(path: str) -> dict:
    """The per-model serving rollup off the span sink: every
    ``serving-request`` summary span folded into per-(model, role)
    rows — request/error/shed counts, p50/p99/p99.9, mean batch fill,
    goodput ratio, summed badput per category, SLO over-target
    fraction when the span carries a target, and the slowest request
    ids (each reconstructible stage-by-stage via reconstruct()).
    jax-free; the dashboard serves this at /api/obs/serving."""
    groups: dict[tuple, list] = {}
    for rec in load_spans(path):
        if rec.get("name") != SERVING_REQUEST_SPAN:
            continue
        a = _attrs(rec)
        model = str(a.get("model", ""))
        role = str(a.get("role", "primary"))
        groups.setdefault((model, role), []).append((rec, a))
    rows = []
    total = 0
    for (model, role), recs in sorted(groups.items()):
        lat = []
        fills = []
        goodput_s = 0.0
        wall_s = 0.0
        bad = {c: 0.0 for c in SERVING_BADPUT_CATEGORIES}
        errors = shed = 0
        slo_target_ms = None
        quant_delta = None
        over_slo = 0
        slowest: list[tuple] = []
        for rec, a in recs:
            ledger = a.get("ledger")
            ledger = ledger if isinstance(ledger, dict) else {}
            try:
                wall = float(ledger.get("wallSeconds", 0.0))
            except (TypeError, ValueError):
                wall = 0.0
            lat.append(wall)
            wall_s += wall
            goodput_s += float(ledger.get("goodputSeconds", 0.0) or 0.0)
            for c, v in (ledger.get("badputSeconds") or {}).items():
                if c in bad:
                    bad[c] += float(v or 0.0)
            outcome = a.get("outcome", "ok")
            if outcome == "shed":
                shed += 1
            elif outcome != "ok":
                errors += 1
            if a.get("fill") is not None:
                try:
                    fills.append(float(a["fill"]))
                except (TypeError, ValueError):
                    pass
            if a.get("slo_p99_ms") is not None:
                try:
                    slo_target_ms = float(a["slo_p99_ms"])
                    if wall * 1e3 > slo_target_ms:
                        over_slo += 1
                except (TypeError, ValueError):
                    pass
            if a.get("quant_delta") is not None:
                # int8 tier's measured accuracy delta (one value per
                # loaded model version; last span wins)
                try:
                    quant_delta = float(a["quant_delta"])
                except (TypeError, ValueError):
                    pass
            slowest.append((wall, str(rec.get("trace_id", ""))))
        lat.sort()
        slowest.sort(reverse=True)
        n = len(recs)
        total += n
        row = {
            "model": model, "role": role, "requests": n,
            "errors": errors, "shed": shed,
            "p50Ms": round(_percentile(lat, 0.50) * 1e3, 3),
            "p99Ms": round(_percentile(lat, 0.99) * 1e3, 3),
            "p999Ms": round(_percentile(lat, 0.999) * 1e3, 3),
            "meanFill": round(sum(fills) / len(fills), 4) if fills
            else None,
            "goodputRatio": round(goodput_s / wall_s, 6) if wall_s
            else 0.0,
            "badputSeconds": {c: round(v, 6) for c, v in bad.items()},
            "slowest": [{"requestId": rid, "wallMs": round(w * 1e3, 3)}
                        for w, rid in slowest[:3]],
        }
        if quant_delta is not None:
            row["quantDelta"] = round(quant_delta, 6)
        if slo_target_ms is not None:
            # p99 target → 1% of requests are allowed over it; the
            # over-target fraction against that budget is the window
            # burn rate the replica registry tracks live
            row["slo"] = {
                "targetP99Ms": slo_target_ms,
                "overTargetRatio": round(over_slo / n, 6) if n else 0.0,
                "compliant": bool(n and over_slo / n <= 0.01),
            }
        rows.append(row)
    return {"models": rows, "requests": total}

# span names the ledger consumes (emitted by the worker — runtime/worker
# + runtime/checkpoint op log; the control-plane names are condition/
# scheduler events: queued/bound/preempted/resized/restarting/...)
SPAN_CKPT_SAVE = "ckpt-save"
SPAN_CKPT_RESTORE = "ckpt-restore"
# per-window MPMD schedule-idle interval (runtime/worker.py sizes it to
# the engine's measured bubble seconds, anchored at the window's tail —
# a modeled attribution inside a real interval, documented in
# docs/operations.md "Goodput accounting")
SPAN_PIPELINE_BUBBLE = "pipeline-bubble"
# tripped numeric-integrity detector (runtime/worker.py emits it with
# the evidence — step, kind, lkg — right before exiting for rollback);
# decompose reads its (lkg, step] range to split replayed steps into
# rollback_recompute. THE anomaly-event literal (tests/test_lint.py
# pins it here).
SPAN_ANOMALY = "anomaly"

# overlap resolution: when two attributed intervals claim the same time,
# the LOWEST number wins. Compile outranks the windows (the first window
# span CONTAINS the first step's compile — that stretch is startup cost,
# not training); recompute outranks goodput (a replayed window is waste
# even though it looks like training); measured worker spans outrank
# inferred control-plane intervals; everything outranks the residual.
_PRIORITY = {
    BADPUT_COMPILE: 0,
    BADPUT_ROLLBACK: 1,
    BADPUT_RECOMPUTE: 2,
    # above goodput: a bubble span carves schedule-idle time OUT of the
    # window interval it overlaps (the worker sizes it to the measured
    # bubble seconds of that window's steps)
    BADPUT_PIPELINE_BUBBLE: 3,
    GOODPUT: 4,
    BADPUT_CHECKPOINT: 5,
    BADPUT_STALL: 6,
    BADPUT_RESIZE: 7,
    BADPUT_QUEUE_WAIT: 8,
    BADPUT_STARTUP: 9,
}

# operator restart reasons that read as a stall (controllers/tpujob.py)
_STALL_REASONS = ("StallTimeout", "WorkerStallTimeout")

# worker activity that ends a startup/resize-downtime interval
_WORKER_ACTIVITY = ("train-start", "first-step", "window", SPAN_CKPT_SAVE,
                    SPAN_CKPT_RESTORE)


def _attrs(span: dict) -> dict:
    a = span.get("attrs")
    return a if isinstance(a, dict) else {}


def _next_activity(spans: list[dict], after: float,
                   names: tuple = _WORKER_ACTIVITY) -> Optional[float]:
    """Start time of the first worker-activity span after ``after``."""
    best = None
    for s in spans:
        if s.get("name") in names and s.get("start", 0.0) > after:
            if best is None or s["start"] < best:
                best = s["start"]
    return best


def _last_activity_end(spans: list[dict], before: float) -> Optional[float]:
    """End of the last worker-activity span before ``before`` — where a
    stalled worker last showed signs of life."""
    best = None
    for s in spans:
        end = s.get("end", s.get("start", 0.0))
        if s.get("name") in _WORKER_ACTIVITY and end < before:
            if best is None or end > best:
                best = end
    return best


def _window_segments(spans: list[dict],
                     rollback_ranges: tuple = ()) -> tuple:
    """Split every ``window`` span into goodput vs recompute via a
    step high-water walk: a window re-covering steps already banked
    before a restart is replay, charged to ``restart_recompute``
    proportionally (the replayed steps run FIRST chronologically).
    ``rollback_ranges`` — (anomaly_time, lkg, trip) per anomaly span —
    reclassifies the replayed steps inside a rollback's (lkg, trip]
    range as ``rollback_recompute``, but only for windows AFTER the
    trip: the original run of those steps was goodput at the time.
    Returns (segments, steps_new, steps_recomputed, steps_rolled_back,
    n_windows)."""
    segments: list[tuple] = []
    high_water = 0
    steps_new = 0
    steps_re = 0
    steps_rb = 0
    windows = 0
    for s in spans:
        if s.get("name") != "window":
            continue
        a = _attrs(s)
        try:
            s1 = int(a.get("step", 0))
            n = int(a.get("steps", 0))
        except (TypeError, ValueError):
            continue
        start = float(s.get("start", 0.0))
        end = float(s.get("end", start))
        if n <= 0 or end <= start:
            continue
        windows += 1
        s0 = s1 - n
        re = min(n, max(0, min(s1, high_water) - s0))
        new = n - re
        re_rb = 0
        if re:
            for at, lkg, trip in rollback_ranges:
                if start >= at:
                    overlap = min(s0 + re, trip) - max(s0, lkg)
                    if overlap > 0:
                        re_rb = max(re_rb, min(re, overlap))
        # chronological order inside the window: the replayed steps run
        # first (rollback replay before restart replay before new work)
        split_rb = start + (end - start) * (re_rb / n)
        split = start + (end - start) * (re / n)
        if re_rb:
            segments.append((start, split_rb, BADPUT_ROLLBACK))
        if re - re_rb:
            segments.append((split_rb, split, BADPUT_RECOMPUTE))
        if new:
            segments.append((split, end, GOODPUT))
        high_water = max(high_water, s1)
        steps_new += new
        steps_re += re
        steps_rb += re_rb
    return segments, steps_new, steps_re, steps_rb, windows


def decompose(spans: list[dict]) -> dict:
    """Fold ONE trace's span records (load_spans order) into the ledger:

    ``{"wallSeconds", "goodputSeconds", "goodputRatio",
    "badputSeconds": {category: seconds — every BADPUT_CATEGORIES key},
    "compileByStartKind": {...}, "steps", "stepsRecomputed",
    "stepsRolledBack", "windows", "chips"}``

    The categories plus goodput sum to wallSeconds exactly (partition by
    construction); ``categories_sum_ok`` is the bench's tolerance check
    against independent wall measurements.
    """
    empty = {
        "wallSeconds": 0.0, "goodputSeconds": 0.0, "goodputRatio": 0.0,
        "badputSeconds": {c: 0.0 for c in BADPUT_CATEGORIES},
        "compileByStartKind": {}, "steps": 0, "stepsRecomputed": 0,
        "stepsRolledBack": 0, "windows": 0, "chips": 0,
    }
    if not spans:
        return empty
    t0 = min(float(s.get("start", 0.0)) for s in spans)
    t1 = max(float(s.get("end", s.get("start", 0.0))) for s in spans)
    if t1 <= t0:
        return empty

    # anomaly-rollback evidence pre-pass: each anomaly span's
    # (lkg, trip] range marks the steps whose replay is the sentinel's
    # recovery cost, not generic restart recompute
    rollback_ranges = []
    for s in spans:
        if s.get("name") != SPAN_ANOMALY:
            continue
        a = _attrs(s)
        try:
            trip = int(a.get("step", 0))
            lkg = int(a.get("lkg") or 0)
        except (TypeError, ValueError):
            continue
        if trip > lkg >= 0:
            rollback_ranges.append(
                (float(s.get("start", 0.0)), lkg, trip))

    segments, steps_new, steps_re, steps_rb, windows = \
        _window_segments(spans, tuple(rollback_ranges))
    compile_by_kind: dict[str, float] = {}
    chips = 0

    open_queue: Optional[float] = None
    for s in spans:
        name = s.get("name")
        start = float(s.get("start", 0.0))
        end = float(s.get("end", start))
        a = _attrs(s)
        if name == "queued":
            if open_queue is None:
                open_queue = start
        elif name == "bound":
            if open_queue is not None:
                segments.append((open_queue, start, BADPUT_QUEUE_WAIT))
                open_queue = None
            try:
                chips = int(a.get("chips", chips)) or chips
            except (TypeError, ValueError):
                pass
            # pod create → worker first activity: the startup stretch
            # (low priority — measured worker spans carve their own time
            # out of it)
            until = _next_activity(spans, start)
            segments.append((start, until if until is not None else t1,
                             BADPUT_STARTUP))
        elif name == "first-step":
            # train() entry → first completed step; dominated by the
            # compile/cache-load/AOT-load rung recorded in start_kind
            try:
                secs = float(a.get("seconds", 0.0))
            except (TypeError, ValueError):
                secs = 0.0
            if secs > 0:
                lo = max(t0, start - secs)
                segments.append((lo, start, BADPUT_COMPILE))
                kind = str(a.get("start_kind", "cold"))
                # clipped to the stream: the attr measures from train()
                # entry, which can predate the job's first span
                compile_by_kind[kind] = \
                    compile_by_kind.get(kind, 0.0) + (start - lo)
        elif name in (SPAN_CKPT_SAVE, SPAN_CKPT_RESTORE):
            if end > start:
                segments.append((start, end, BADPUT_CHECKPOINT))
        elif name == SPAN_PIPELINE_BUBBLE:
            if end > start:
                segments.append((start, end, BADPUT_PIPELINE_BUBBLE))
        elif name == "resized":
            # binding rewritten → gang restarts at the new shape; the
            # downtime runs to the worker's next sign of life
            until = _next_activity(spans, start)
            segments.append((start, until if until is not None else t1,
                             BADPUT_RESIZE))
        elif name == "restarting":
            # restart downtime (teardown → the recreated gang's first
            # sign of life) is startup badput; for a watchdog-triggered
            # restart the wedged stretch BEFORE the teardown — last
            # worker activity → the restarting transition — is stall
            # (the flight recorder's dump covers the same stretch from
            # inside the worker)
            until = _next_activity(spans, start)
            segments.append((start, until if until is not None else t1,
                             BADPUT_STARTUP))
            if a.get("reason") in _STALL_REASONS:
                last = _last_activity_end(spans, start)
                if last is not None and start > last:
                    segments.append((last, start, BADPUT_STALL))
    if open_queue is not None:
        # still waiting at the end of the stream (never bound)
        segments.append((open_queue, t1, BADPUT_QUEUE_WAIT))

    # ---- the sweep: partition [t0, t1] by priority ----------------------
    # Two-pointer event sweep, O(n log n) in span count: this runs
    # inside the operator's reconcile (_finalize_ledger) and on every
    # dashboard request, so a multi-day job's thousands of window spans
    # must not turn one decompose into a quadratic scan.
    totals = {c: 0.0 for c in BADPUT_CATEGORIES}
    totals[GOODPUT] = 0.0
    segments = [(max(t0, a), min(t1, b), cat) for a, b, cat in segments
                if min(t1, b) > max(t0, a)]
    bounds = sorted({t0, t1, *(a for a, _b, _c in segments),
                     *(b for _a, b, _c in segments)})
    starts = sorted(segments, key=lambda s: s[0])
    ends = sorted(segments, key=lambda s: s[1])
    by_priority = sorted(_PRIORITY, key=_PRIORITY.__getitem__)
    active = {c: 0 for c in _PRIORITY}
    si = ei = 0
    for lo, hi in zip(bounds, bounds[1:]):
        # a segment [a, b] covers [lo, hi) iff a <= lo and b > lo
        # (every b is itself a boundary, so b > lo equals b >= hi)
        while si < len(starts) and starts[si][0] <= lo:
            active[starts[si][2]] += 1
            si += 1
        while ei < len(ends) and ends[ei][1] <= lo:
            active[ends[ei][2]] -= 1
            ei += 1
        cat = next((c for c in by_priority if active[c] > 0),
                   BADPUT_OTHER)
        totals[cat] += hi - lo

    wall = t1 - t0
    goodput = totals.pop(GOODPUT)
    return {
        "wallSeconds": round(wall, 6),
        "goodputSeconds": round(goodput, 6),
        "goodputRatio": round(goodput / wall, 6) if wall else 0.0,
        "badputSeconds": {c: round(v, 6) for c, v in totals.items()},
        "compileByStartKind": {k: round(v, 6)
                               for k, v in sorted(compile_by_kind.items())},
        "steps": steps_new,
        "stepsRecomputed": steps_re,
        "stepsRolledBack": steps_rb,
        "windows": windows,
        "chips": chips,
    }


def ledger_for(path: str, trace_id: str) -> dict:
    """One job's ledger straight from the span sink."""
    return decompose(load_spans(path, trace_id=trace_id))


def categories_sum_ok(ledger: dict, tolerance: float = 0.02) -> bool:
    """goodput + every badput category must re-add to wall-clock within
    ``tolerance`` (fractional). Exact by construction today; the check
    guards the partition invariant against future category edits."""
    wall = ledger.get("wallSeconds", 0.0)
    total = ledger.get("goodputSeconds", 0.0) + \
        sum(ledger.get("badputSeconds", {}).values())
    if wall <= 0:
        return total == 0
    return math.isclose(total, wall, rel_tol=tolerance, abs_tol=1e-6)


def annotation_payload(ledger: dict) -> str:
    """The compact final-ledger JSON the operator stamps on completion."""
    return json.dumps({
        "goodputRatio": ledger["goodputRatio"],
        "wallSeconds": round(ledger["wallSeconds"], 3),
        "goodputSeconds": round(ledger["goodputSeconds"], 3),
        "badputSeconds": {c: round(v, 3)
                          for c, v in ledger["badputSeconds"].items()},
        "stepsRecomputed": ledger["stepsRecomputed"],
    }, sort_keys=True)


def _ledger_families(reg) -> tuple:
    ratio = reg.gauge(
        "kftpu_job_goodput_ratio",
        "fraction of the job's wall clock spent on productive (never "
        "re-executed) train steps", labels=("namespace", "name"))
    # a counter via the registry's snapshot bridge (set() for sources
    # that keep their own monotonic totals — the ledger IS the
    # bookkeeper): keeps the Prometheus _total-means-counter convention
    # while exporting the final cumulative seconds in one shot
    seconds = reg.counter(
        "kftpu_job_badput_seconds_total",
        "job wall-clock seconds lost per badput category "
        "(docs/operations.md 'Goodput accounting')",
        labels=("namespace", "name", "category"))
    return ratio, seconds


def export_job_ledger(namespace: str, name: str, ledger: dict,
                      registry=None) -> None:
    """Export one job's ledger as the scrape-surface series:
    ``kftpu_job_goodput_ratio{namespace,name}`` and
    ``kftpu_job_badput_seconds_total{namespace,name,category}``."""
    reg = registry if registry is not None else obsreg.default_registry()
    ratio, seconds = _ledger_families(reg)
    ratio.labels(namespace=namespace, name=name).set(
        ledger["goodputRatio"])
    for cat in BADPUT_CATEGORIES:
        seconds.labels(namespace=namespace, name=name, category=cat).set(
            ledger["badputSeconds"].get(cat, 0.0))


def remove_job_ledger(namespace: str, name: str, registry=None) -> None:
    """Drop a deleted job's ledger series — a long-lived operator must
    not export every finished job's decomposition forever (the
    kftpu_job_phase pruning rule)."""
    reg = registry if registry is not None else obsreg.default_registry()
    ratio, seconds = _ledger_families(reg)
    ratio.remove(namespace=namespace, name=name)
    for cat in BADPUT_CATEGORIES:
        seconds.remove(namespace=namespace, name=name, category=cat)


def cluster_rollup(path: str) -> dict:
    """The cluster-level chip-hour rollup: every trace in the sink,
    weighted by its bound gang width. ``chipHours`` decomposes the
    fleet's chip-time the way a single job's ledger decomposes its
    wall clock (jobs that never bound contribute wait with zero chips —
    reported in ``jobsNeverBound``, not silently dropped)."""
    by_trace: dict[str, list] = {}
    for rec in load_spans(path):
        tid = rec.get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(rec)
    chip_sec = {c: 0.0 for c in BADPUT_CATEGORIES}
    goodput_sec = 0.0
    wall_sec = 0.0
    never_bound = 0
    jobs = []
    for tid, spans in sorted(by_trace.items()):
        ledger = decompose(spans)
        chips = ledger["chips"]
        if not chips:
            never_bound += 1
        goodput_sec += ledger["goodputSeconds"] * chips
        wall_sec += ledger["wallSeconds"] * chips
        for c, v in ledger["badputSeconds"].items():
            chip_sec[c] += v * chips
        jobs.append({"traceId": tid, "chips": chips,
                     "goodputRatio": ledger["goodputRatio"],
                     "wallSeconds": ledger["wallSeconds"]})
    return {
        "jobs": jobs,
        "jobsNeverBound": never_bound,
        "chipHours": {
            "total": round(wall_sec / 3600.0, 6),
            GOODPUT: round(goodput_sec / 3600.0, 6),
            "badput": {c: round(v / 3600.0, 6)
                       for c, v in chip_sec.items()},
        },
        "goodputRatio": round(goodput_sec / wall_sec, 6)
        if wall_sec else 0.0,
    }
