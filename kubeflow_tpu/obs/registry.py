"""Dependency-free Prometheus-text metrics registry.

The platform's four interacting subsystems (scheduler, operator, input
pipeline, train loop) each kept their own telemetry — a JSONL
MetricsLogger, heartbeat annotations, two hand-rolled text expositions.
This registry is the one shared substrate under all of them: Counter /
Gauge / Histogram families with labels, a process-wide default registry
every in-process component instruments against, and ``render()``
emitting the standard Prometheus text exposition (format 0.0.4) that
``obs/http.py`` serves on ``/metrics``.

Design constraints, in order:

- **Dependency-free.** The container ships no prometheus_client; this is
  the text format from the spec, nothing more.
- **Hot-path cheap.** A counter increment is a dict-free attribute walk
  plus one lock'd float add (~0.2 µs). Instrumented call sites resolve
  their labeled child ONCE and hold it (``family.labels(...)`` returns a
  stable handle), so the per-event cost never includes label hashing.
  ``bench.py --mode obs`` holds the line: registry + span overhead must
  stay under 1% of a training step.
- **Disable-able.** ``KFTPU_OBS_DISABLE=1`` makes the default registry
  hand out no-op metrics — the uninstrumented arm of the overhead A/B,
  and the escape hatch if instrumentation is ever implicated in an
  incident.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Optional, Sequence

# kill switch for the process-wide default registry (bench A/B baseline;
# operational escape hatch). Read when the default registry is created.
OBS_DISABLE_ENV = "KFTPU_OBS_DISABLE"

# Prometheus-conventional latency buckets, widened at the top for the
# control-plane paths (reconcile passes, queue waits span ms → minutes).
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0)

_NAME_OK = set("abcdefghijklmnopqrstuvwxyz"
               "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or any(c not in _NAME_OK for c in name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt(v: float) -> str:
    """Exposition value format: integers without the trailing ``.0`` —
    wire-compatible with the hand-rolled expositions this registry
    replaced (``kubeflow_availability 1``, not ``1.0``)."""
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class _NullChild:
    """No-op metric handle (disabled registry): every operation, including
    labels(), returns self — call sites stay branch-free."""

    def labels(self, **kv) -> "_NullChild":
        return self

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def remove(self, **kv) -> None:
        pass

    @property
    def value(self) -> float:
        return 0.0


_NULL = _NullChild()


class _Child:
    """One labeled series of a family. Thread-safe via the family lock."""

    __slots__ = ("_family", "_lock", "_value", "_buckets", "_counts",
                 "_sum", "_count")

    def __init__(self, family: "_Family"):
        self._family = family
        self._lock = family._lock
        self._value = 0.0
        if family.kind == "histogram":
            self._buckets = family.buckets
            self._counts = [0] * len(self._buckets)
            self._sum = 0.0
            self._count = 0

    # counters / gauges -----------------------------------------------------

    def inc(self, amount: float = 1.0) -> None:
        if self._family.kind == "counter" and amount < 0:
            raise ValueError("counter can only increase")
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        if self._family.kind != "gauge":
            raise TypeError(f"{self._family.kind} cannot dec()")
        with self._lock:
            self._value -= amount

    def set(self, value: float) -> None:
        """Gauges set freely; counters accept set() ONLY as the snapshot
        bridge for sources that keep their own monotonic totals (the
        model server's per-servable stats) — the exposition stays a
        counter, the source stays the one bookkeeper."""
        if self._family.kind == "histogram":
            raise TypeError("histogram cannot set()")
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    # histograms ------------------------------------------------------------

    def observe(self, value: float) -> None:
        if self._family.kind != "histogram":
            raise TypeError(f"{self._family.kind} cannot observe()")
        v = float(value)
        with self._lock:
            for i, le in enumerate(self._buckets):
                if v <= le:
                    self._counts[i] += 1
                    break
            self._sum += v
            self._count += 1

    def bucket_counts(self) -> dict:
        """Cumulative bucket counts keyed by upper bound (inf included)."""
        return self._snapshot()[0]

    def _snapshot(self) -> tuple:
        """(cumulative buckets, sum, count) under ONE lock acquisition:
        an observe() landing between two reads would otherwise scrape an
        exposition whose _count disagrees with its +Inf bucket."""
        with self._lock:
            out = {}
            acc = 0
            for le, n in zip(self._buckets, self._counts):
                acc += n
                out[le] = acc
            out[math.inf] = self._count
            return out, self._sum, self._count


class _Family:
    """One named metric: TYPE/HELP plus its labeled children."""

    def __init__(self, name: str, help: str, kind: str,
                 label_names: Sequence[str],
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = _check_name(name)
        self.help = help
        self.kind = kind
        self.label_names = tuple(label_names)
        for ln in self.label_names:
            _check_name(ln)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._lock = threading.Lock()
        self._children: dict[tuple, _Child] = {}
        if not self.label_names:
            # unlabeled series exist (as zero) from registration — a
            # scrape must see a fresh prober's counters, not absence
            self._children[()] = _Child(self)

    def labels(self, **kv) -> _Child:
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.label_names)}")
        key = tuple(str(kv[ln]) for ln in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _Child(self)
                self._children[key] = child
        return child

    def remove(self, **kv) -> None:
        """Drop one labeled series (a job that no longer exists must not
        export its last phase forever)."""
        key = tuple(str(kv.get(ln, "")) for ln in self.label_names)
        with self._lock:
            self._children.pop(key, None)

    def children(self) -> dict[tuple, "_Child"]:
        """Snapshot of the labeled children (label-value tuple -> child)
        — the read surface dashboards/tests use to walk series without
        reaching into _children."""
        with self._lock:
            return dict(self._children)

    # unlabeled families proxy the single default child ---------------------

    def _default(self) -> _Child:
        if self.label_names:
            raise ValueError(
                f"{self.name} declares labels {self.label_names}; "
                "use .labels(...)")
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    @property
    def value(self) -> float:
        return self._default().value

    def bucket_counts(self) -> dict:
        return self._default().bucket_counts()

    # exposition ------------------------------------------------------------

    def _labels_str(self, key: tuple, extra: str = "") -> str:
        parts = [f'{ln}="{_escape_label(v)}"'
                 for ln, v in zip(self.label_names, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            children = sorted(self._children.items())
        for key, child in children:
            if self.kind == "histogram":
                buckets, s, c = child._snapshot()
                for le, n in buckets.items():
                    le_pair = 'le="' + _fmt(le) + '"'
                    lines.append(f"{self.name}_bucket"
                                 f"{self._labels_str(key, le_pair)} {n}")
                lines.append(f"{self.name}_sum{self._labels_str(key)} "
                             f"{_fmt(s)}")
                lines.append(f"{self.name}_count{self._labels_str(key)} {c}")
            else:
                lines.append(f"{self.name}{self._labels_str(key)} "
                             f"{_fmt(child.value)}")
        return lines


class Registry:
    """A set of metric families. Components that must not share state
    across instances (probers, model servers — several can coexist in
    one test process) hold their own Registry; everything that IS the
    process (scheduler pass, reconcilers, the worker loop) instruments
    the module-level default registry, which the process's ``/metrics``
    serves."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get(self, name: str, help: str, kind: str,
             labels: Sequence[str],
             buckets: Sequence[float] = DEFAULT_BUCKETS):
        if not self.enabled:
            return _NULL
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                # idempotent re-registration (modules re-instrument on
                # re-import); a CHANGED shape is a programming error
                if fam.kind != kind or fam.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} re-registered as {kind}"
                        f"{tuple(labels)}; existing: {fam.kind}"
                        f"{fam.label_names}")
                return fam
            fam = _Family(name, help, kind, labels, buckets=buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str,
                labels: Sequence[str] = ()) -> _Family:
        return self._get(name, help, "counter", labels)

    def gauge(self, name: str, help: str,
              labels: Sequence[str] = ()) -> _Family:
        return self._get(name, help, "gauge", labels)

    def histogram(self, name: str, help: str, labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        return self._get(name, help, "histogram", labels, buckets=buckets)

    def family(self, name: str) -> Optional[_Family]:
        """The registered family, or None — the public read accessor
        (registration stays through counter/gauge/histogram)."""
        if not self.enabled:
            return None
        with self._lock:
            return self._families.get(name)

    def series_counts(self) -> dict[str, int]:
        """Live series (labeled children) per family — the registry's
        own cardinality self-audit. A leaked per-job series shows up
        here long before a scrape slows down."""
        with self._lock:
            families = list(self._families.values())
        return {fam.name: len(fam.children()) for fam in families}

    def render(self) -> str:
        """The Prometheus text exposition, families in name order."""
        lines: list[str] = []
        with self._lock:
            families = sorted(self._families.items())
        for _, fam in families:
            lines.extend(fam.render())
        return "\n".join(lines) + "\n" if lines else ""


# ----------------------------------------------------------- default registry

_default: Optional[Registry] = None
_default_lock = threading.Lock()


def default_registry() -> Registry:
    """The process-wide registry (created on first use; honors
    KFTPU_OBS_DISABLE at creation time)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Registry(
                    enabled=not os.environ.get(OBS_DISABLE_ENV))
    return _default


def reset_default_registry() -> None:
    """Drop the process-wide registry so the next use re-reads
    KFTPU_OBS_DISABLE and starts from zero — the seam the overhead
    bench's on/off arms and tests flip."""
    global _default
    with _default_lock:
        _default = None


def counter(name: str, help: str, labels: Sequence[str] = ()):
    return default_registry().counter(name, help, labels)


def gauge(name: str, help: str, labels: Sequence[str] = ()):
    return default_registry().gauge(name, help, labels)


def histogram(name: str, help: str, labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS):
    return default_registry().histogram(name, help, labels, buckets=buckets)


# the cardinality self-audit gauge (observability watching itself):
# metric-series leaks are a control-plane scale risk of their own
OBS_SERIES_FAMILY = "kftpu_obs_series_total"


def export_series_totals(registry: Optional[Registry] = None) -> dict:
    """Refresh ``kftpu_obs_series_total{family}`` from the registry's
    live series counts (stale family rows are removed — a pruned family
    must not keep exporting its last count). Called on scrape/endpoint
    boundaries, not per mutation; returns the counts it exported."""
    reg = registry if registry is not None else default_registry()
    counts = reg.series_counts()
    gauge = reg.gauge(OBS_SERIES_FAMILY,
                      "live series (labeled children) per metric family",
                      labels=("family",))
    if gauge is _NULL:   # disabled registry: nothing to export
        return counts
    # count the self-audit family itself AFTER registration so the
    # export is internally consistent (it appears in its own table)
    counts[OBS_SERIES_FAMILY] = len(counts) + (
        0 if OBS_SERIES_FAMILY in counts else 1)
    for stale_key in set(gauge.children()) - {
            (name,) for name in counts}:
        gauge.remove(family=stale_key[0])
    for name, n in counts.items():
        gauge.labels(family=name).set(n)
    return counts
