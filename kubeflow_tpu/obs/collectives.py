"""HLO communication analyzer: per-collective ICI/DCN accounting.

The multi-slice roadmap item ("Multi-slice DCN training") is judged on
signals nobody could measure until now — DCN bytes/step, per-link
collective traffic, and the "involuntary full rematerialization" red
flag the DCN dryrun still logs (MULTICHIP_r05). This module makes
cross-slice communication a first-class measured quantity: it walks a
compiled train step's HLO text (``compiled.as_text()`` — the PR 9
``build_compiled`` object, so it works identically on cold, cache-warm,
and AOT-loaded executables), extracts every collective op, computes
modeled bytes from result shapes x dtype, and classifies each op ICI vs
DCN by intersecting its replica groups with the mesh's slice membership
(DCN-major mesh order, parallel/mesh.py).

This is also the single home of the HLO collective-op vocabulary:
``collective_counts`` (formerly bench-local) lives here, and
tests/test_lint.py pins the op literals to this one module so the bench
and the analyzer can never drift.

Modeling conventions (docs/operations.md "Communication observability"):

- **Participant ids are device-assignment positions.** With
  ``use_global_device_ids=true`` a replica-group entry ``p`` names the
  p-th device of the executable's device assignment — for a jit over a
  Mesh that is ``mesh.devices.flatten()`` order, NOT the raw jax device
  id. ``slice_assignment`` maps those positions to slice ids.
- **Wire bytes are per-participant ring loads.** For a group of n over
  payload P: all-reduce moves ``2*P*(n-1)/n`` (reduce + broadcast
  halves), all-gather / all-to-all ``P*(n-1)/n`` (P = the full gathered
  result), reduce-scatter ``P*(n-1)/n`` with P = the full pre-scatter
  input (result x n). A collective-permute moves its payload once per
  pair; we report the crossing fraction.
- **The ICI/DCN split is hierarchical.** A group spanning k slices of
  n_local participants each is modeled as an intra-slice phase (ICI,
  the same formula at n_local) plus an inter-slice phase (DCN, the same
  formula at k) — the decomposition a multislice backend actually runs.
- **Conservation, stated up front:** reduce-scatter + all-gather moves
  exactly what one all-reduce moves (that is how rings implement
  all-reduce), so a ZeRO-2 arm's TOTAL wire bytes equal the replicated
  arm's. ``modeled_update_dcn_bytes`` therefore isolates the phase the
  sharded update owns: the replicated update needs the reduced gradient
  broadcast to EVERY replica (factor 2), the sharded update only
  re-gathers final params (factor 1) — the broadcast redundancy Xu et
  al.'s rewrite removes. The totals table is always reported beside it.
"""

from __future__ import annotations

import math
import os
import re
from dataclasses import dataclass, field
from typing import Optional, Sequence

# ---------------------------------------------------------------------------
# The one HLO collective vocabulary (lint-pinned: these literals appear in
# THIS module only — bench.py and every other consumer imports them).

COLLECTIVE_OPS = (
    "all-reduce",
    "reduce-scatter",
    "all-gather",
    "all-to-all",
    "collective-permute",
)

# XLA:TPU converts collectives to start/done pairs; only the -start op
# names the operands and groups, so the parser counts it alone (the sync
# form still matches bare, and "-done" lines never match).
ASYNC_START_FORMS = (
    "all-reduce-start",
    "reduce-scatter-start",
    "all-gather-start",
    "all-to-all-start",
    "collective-permute-start",
)

# link classes
LINK_ICI = "ici"      # every participant pair inside one slice
LINK_DCN = "dcn"      # at least one group/pair crosses a slice boundary
LINK_LOCAL = "local"  # degenerate single-participant groups: no traffic

# bandwidth-model knobs (GB/s). Order-of-magnitude models for the
# modeled-seconds column, not measurements: v5e ICI is O(100 GB/s) per
# chip, a DCN NIC share is O(50 Gbit/s) = 6.25 GB/s per host.
ICI_GBPS_ENV = "KFTPU_COMM_ICI_GBPS"
DCN_GBPS_ENV = "KFTPU_COMM_DCN_GBPS"
DEFAULT_ICI_GBPS = 90.0
DEFAULT_DCN_GBPS = 6.25

# worker wiring: profile mode (env) and the span the profile lands under
COMM_PROFILE_ENV = "KFTPU_COMM_PROFILE"   # "auto" (default) | "1" | "0"
COMM_PROFILE_SPAN = "comm-profile"
# ops carried verbatim on the span (largest first); the full table is
# available from bench --mode comm / the dryrun
COMM_TOP_OPS_ENV = "KFTPU_COMM_TOP_OPS"

# Ops whose source metadata lands in these files belong to the
# weight-update region (the optimizer update + param re-gather the
# TrainStepBuilder emits); everything else is model forward/backward.
# The detector treats an op with NO metadata as model-region —
# conservative: an unattributed DCN reshard should flag, not hide.
UPDATE_REGION_FILES = ("trainstep.py",)

# Ops emitted by the pipeline engines' OWN send/recv (the GPipe
# ppermute in parallel/pipeline.py; any collective a multislice stage
# program carries) are DELIBERATE activation traffic: a pipeline mesh
# spanning slices pays the DCN hop by design, and the full-reshard
# detector must never misread it as the involuntary-remat pathology —
# ops attributed to these files carry phase="pipeline" and the detector
# skips them.
PIPELINE_REGION_FILES = ("pipeline.py", "multislice.py")

# op phases (the by-(link, op) table's per-row breakdown)
PHASE_MODEL = "model"        # forward/backward
PHASE_UPDATE = "update"      # optimizer update / param re-gather
PHASE_PIPELINE = "pipeline"  # deliberate stage send/recv

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8e4m3fn": 1, "f8e4m3": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "s4": 1, "u4": 1, "pred": 1, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(%?[\w.\-]+)\s*=\s*(.*?)\s*"
    r"(" + "|".join(COLLECTIVE_OPS) + r")(-start)?\(([^)]*)\)")
_GROUPS_LIT_RE = re.compile(
    r"replica_groups=\{(\{[0-9,]*\}(?:,\{[0-9,]*\})*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
    r"(?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(
    r"source_target_pairs=\{(\{[0-9,]+\}(?:,\{[0-9,]+\})*)\}")
# matched independently: one lazy regex with optional groups can skip a
# present source_file entirely (zero-width optional match)
_META_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_META_SRC_RE = re.compile(r'source_file="([^"]*)"')
_META_LINE_RE = re.compile(r"source_line=(\d+)")


@dataclass
class CollectiveOp:
    """One collective instruction from optimized HLO, with its modeled
    per-step link traffic."""

    name: str                 # HLO instruction name (%all-gather.7)
    kind: str                 # one of COLLECTIVE_OPS
    is_async_start: bool
    # (dtype, dims) of every result-shape bracket on the op line
    result_shapes: list = field(default_factory=list)
    payload_bytes: int = 0    # modeled logical payload (see payload rules)
    groups: Optional[list] = None           # expanded replica groups
    pairs: Optional[list] = None            # collective-permute pairs
    operands: list = field(default_factory=list)  # operand names
    op_name: str = ""         # metadata op_name (jvp(...)/transpose(...))
    source_file: str = ""     # metadata source_file basename
    source_line: int = 0
    # filled by classification
    link: str = LINK_LOCAL
    slices_spanned: int = 1
    group_size: int = 1
    dcn_bytes: float = 0.0
    ici_bytes: float = 0.0
    axes: tuple = ()          # mesh axes the group varies over (if known)

    @property
    def in_update_region(self) -> bool:
        return os.path.basename(self.source_file) in UPDATE_REGION_FILES

    @property
    def phase(self) -> str:
        """Which region of the step this op belongs to: "pipeline"
        (deliberate stage send/recv — detector-exempt), "update"
        (optimizer/param re-gather), else "model"."""
        base = os.path.basename(self.source_file)
        if base in PIPELINE_REGION_FILES:
            return PHASE_PIPELINE
        if base in UPDATE_REGION_FILES:
            return PHASE_UPDATE
        return PHASE_MODEL

    def to_dict(self) -> dict:
        return {
            "name": self.name, "kind": self.kind, "link": self.link,
            "phase": self.phase,
            "payloadBytes": int(self.payload_bytes),
            "dcnBytes": round(self.dcn_bytes, 1),
            "iciBytes": round(self.ici_bytes, 1),
            "groupSize": self.group_size,
            "slicesSpanned": self.slices_spanned,
            "axes": list(self.axes),
            "opName": self.op_name, "sourceFile": self.source_file,
            "sourceLine": self.source_line,
            "updateRegion": self.in_update_region,
        }


def _parse_shapes(shape_str: str) -> list:
    return [(dt, tuple(int(d) for d in dims.split(",") if d))
            for dt, dims in _SHAPE_RE.findall(shape_str)]


def _shape_bytes(dt: str, dims: tuple) -> int:
    n = 1
    for d in dims:
        n *= d
    return n * _DTYPE_BYTES.get(dt, 4)


def _payload_bytes(kind: str, is_start: bool, shapes: list) -> int:
    """Modeled logical payload from the op's result shapes.

    Sync forms: the sum of all result shapes (a tuple result is a
    combined collective — each element is real payload). Async -start
    forms of all-gather / all-to-all / collective-permute return the
    tuple (operands..., results...); count only the result half so the
    operand copy is not double-charged. all-reduce-start results are
    already result-shaped (no operand echo)."""
    if (is_start and kind in ("all-gather", "all-to-all",
                              "collective-permute")
            and len(shapes) >= 2 and len(shapes) % 2 == 0):
        shapes = shapes[len(shapes) // 2:]
    return sum(_shape_bytes(dt, dims) for dt, dims in shapes)


def _expand_groups(line: str) -> Optional[list]:
    """replica_groups in either HLO syntax, expanded to explicit id
    lists: literal ``{{0,4},{1,5}}`` or iota ``[G,S]<=[dims]T(perm)``
    (iota of prod(dims), reshaped to dims, transposed by perm, flattened
    row-major, split into G groups of S)."""
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        num_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        total = 1
        for d in dims:
            total *= d
        flat = list(range(total))
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            # index math instead of numpy: this module must stay
            # importable jax/numpy-free (dashboard, lint, operator)
            strides = [0] * len(dims)
            acc = 1
            for i in range(len(dims) - 1, -1, -1):
                strides[i] = acc
                acc *= dims[i]
            new_dims = [dims[p] for p in perm]
            out = []
            idx = [0] * len(new_dims)
            for _ in range(total):
                src = sum(idx[i] * strides[perm[i]]
                          for i in range(len(perm)))
                out.append(src)
                for i in range(len(new_dims) - 1, -1, -1):
                    idx[i] += 1
                    if idx[i] < new_dims[i]:
                        break
                    idx[i] = 0
            flat = out
        return [flat[i * group_size:(i + 1) * group_size]
                for i in range(num_groups)]
    m = _GROUPS_LIT_RE.search(line)
    if m:
        return [[int(x) for x in g.split(",")] if g else []
                for g in re.findall(r"\{([0-9,]*)\}", m.group(1))]
    return None


def parse_hlo_collectives(hlo_text: str) -> list[CollectiveOp]:
    """Every collective instruction in the module, unclassified (no
    slice map yet). ``-done`` lines never match (the ``(`` must follow
    the opcode or its ``-start`` suffix directly), so async pairs are
    counted exactly once."""
    ops = []
    for line in hlo_text.splitlines():
        m = _OP_RE.match(line)
        if not m:
            continue
        name, shape_str, kind, start_sfx, operand_str = m.groups()
        shapes = _parse_shapes(shape_str)
        pairs = None
        if kind == "collective-permute":
            pm = _PAIRS_RE.search(line)
            if pm:
                pairs = [tuple(int(x) for x in p.split(","))
                         for p in re.findall(r"\{([0-9,]+)\}",
                                             pm.group(1))]
        mo = _META_OPNAME_RE.search(line)
        ms = _META_SRC_RE.search(line)
        ml = _META_LINE_RE.search(line)
        ops.append(CollectiveOp(
            name=name.lstrip("%"),
            kind=kind,
            is_async_start=bool(start_sfx),
            result_shapes=shapes,
            payload_bytes=_payload_bytes(kind, bool(start_sfx), shapes),
            groups=_expand_groups(line),
            pairs=pairs,
            operands=[o.strip().split(" ")[-1].lstrip("%")
                      for o in operand_str.split(",") if o.strip()],
            op_name=mo.group(1) if mo else "",
            source_file=ms.group(1) if ms else "",
            source_line=int(ml.group(1)) if ml else 0,
        ))
    return ops


def collective_counts(hlo_text: str) -> dict:
    """Count the weight-update collectives in compiled HLO:
    reduce-scatter, all-gather, and NON-scalar all-reduce ops (a scalar
    f32[] all-reduce is the loss/grad-norm mean, not a full-gradient
    reduction). Async forms count via their ``-start`` op. The
    acceptance signal for the sharded path is reduce_scatter > 0,
    all_gather > 0, all_reduce_nonscalar == 0 (PR 1; promoted here from
    bench.py so bench and analyzer share ONE vocabulary)."""
    counts = {"reduce_scatter": 0, "all_gather": 0,
              "all_reduce_nonscalar": 0}
    for op in parse_hlo_collectives(hlo_text):
        if op.kind == "reduce-scatter":
            counts["reduce_scatter"] += 1
        elif op.kind == "all-gather":
            counts["all_gather"] += 1
        elif op.kind == "all-reduce" and \
                any(dims for _, dims in op.result_shapes):
            counts["all_reduce_nonscalar"] += 1
    return counts


# ---------------------------------------------------------------------------
# classification


def slice_assignment(mesh, num_slices: int) -> list[int]:
    """Participant id → slice id for the given mesh.

    Participant ids are positions in ``mesh.devices.flatten()`` (the jit
    device assignment). Real TPU devices carry ``slice_index``; virtual
    CPU devices fall back to ``id // chips_per_slice`` — valid because
    the DCN-major mesh order keeps the enumeration slice-contiguous
    (the dryrun asserts row 0 == slice 0's devices)."""
    devs = [d for d in mesh.devices.flat]
    per_slice = max(1, len(devs) // max(1, num_slices))
    out = []
    for d in devs:
        si = getattr(d, "slice_index", None)
        out.append(int(si) if si is not None else d.id // per_slice)
    return out


def _axes_of_group(group: list, mesh_axes) -> tuple:
    """Mesh axes the group's members vary over (mesh_axes = ordered
    (name, size) pairs; participant id = row-major position)."""
    if not mesh_axes or len(group) < 2:
        return ()
    names = [a for a, _ in mesh_axes]
    sizes = [s for _, s in mesh_axes]
    coords = []
    for p in group:
        c, rem = [], p
        for s in reversed(sizes):
            c.append(rem % s)
            rem //= s
        coords.append(list(reversed(c)))
    varying = []
    for i, name in enumerate(names):
        if len({c[i] for c in coords}) > 1:
            varying.append(name)
    return tuple(varying)


def _ring_factor(kind: str) -> float:
    return 2.0 if kind == "all-reduce" else 1.0


def _classify_op(op: CollectiveOp, slice_of: Sequence[int],
                 mesh_axes=None) -> None:
    n_total = len(slice_of)
    if op.kind == "collective-permute" and op.pairs is not None:
        # same out-of-range defense as the replica-group path: a pair
        # id beyond the slice map (wrong mesh passed) is skipped, not
        # an IndexError
        valid = [(s, t) for s, t in op.pairs
                 if 0 <= s < n_total and 0 <= t < n_total]
        real = [(s, t) for s, t in valid if s != t]
        crossing = [(s, t) for s, t in real
                    if slice_of[s] != slice_of[t]]
        op.group_size = len(op.pairs)
        op.slices_spanned = len({slice_of[s] for s, _ in valid}
                                | {slice_of[t] for _, t in valid}) \
            if valid else 1
        if not real:
            op.link = LINK_LOCAL
            return
        frac_dcn = len(crossing) / len(real)
        op.link = LINK_DCN if crossing else LINK_ICI
        op.dcn_bytes = op.payload_bytes * frac_dcn
        op.ici_bytes = op.payload_bytes * (1.0 - frac_dcn)
        return
    groups = op.groups
    if not groups or not any(groups):
        # empty replica_groups = one group of every participant
        groups = [list(range(n_total))]
    g0 = max(groups, key=len)
    n = len(g0)
    op.group_size = n
    if mesh_axes:
        op.axes = _axes_of_group(g0, mesh_axes)
    if n <= 1:
        op.link = LINK_LOCAL
        op.slices_spanned = 1
        return
    k = len({slice_of[p] for p in g0 if 0 <= p < n_total}) or 1
    op.slices_spanned = k
    n_local = max(1, n // k)
    f = _ring_factor(op.kind)
    # full logical payload: reduce-scatter's line shows the scattered
    # RESULT, so the pre-scatter input is result x group size
    full = op.payload_bytes * (n if op.kind == "reduce-scatter" else 1)
    op.link = LINK_DCN if k > 1 else LINK_ICI
    if k > 1:
        op.dcn_bytes = f * full * (k - 1) / k
    if n_local > 1:
        op.ici_bytes = f * full * (n_local - 1) / n_local


@dataclass
class CommProfile:
    """Per-step communication profile of one compiled train step."""

    ops: list                   # list[CollectiveOp], classified
    num_slices: int
    ici_gbps: float
    dcn_gbps: float

    @property
    def dcn_bytes_per_step(self) -> float:
        return sum(o.dcn_bytes for o in self.ops)

    @property
    def ici_bytes_per_step(self) -> float:
        return sum(o.ici_bytes for o in self.ops)

    def collectives(self, link: str) -> int:
        return sum(1 for o in self.ops if o.link == link)

    def by_link_op(self) -> dict:
        """{(link, kind): {"count", "bytes"}} — the gauge label space.

        Counts bucket each op under ITS link class; bytes bucket each
        op's ICI-phase bytes under (ici, kind) and DCN-phase bytes
        under (dcn, kind) — a DCN-crossing collective has BOTH phases,
        so this is what makes the per-link gauge sums reconcile with
        ``ici_bytes_per_step`` / ``dcn_bytes_per_step`` (a DCN row may
        therefore carry a zero-count ici sibling row). Each row also
        breaks its count down by op phase (``phases``: model / update /
        pipeline) — deliberate pipeline send/recv traffic is visibly
        labeled, never mistakable for a pathological reshard (the
        detector skips phase=pipeline ops outright)."""
        out: dict = {}

        def row(link, kind):
            return out.setdefault((link, kind),
                                  {"count": 0, "bytes": 0.0,
                                   "phases": {}})

        for o in self.ops:
            r = row(o.link, o.kind)
            r["count"] += 1
            r["phases"][o.phase] = r["phases"].get(o.phase, 0) + 1
            if o.dcn_bytes:
                row(LINK_DCN, o.kind)["bytes"] += o.dcn_bytes
            if o.ici_bytes:
                row(LINK_ICI, o.kind)["bytes"] += o.ici_bytes
        return out

    @property
    def modeled_ici_seconds(self) -> float:
        return self.ici_bytes_per_step / (self.ici_gbps * 1e9)

    @property
    def modeled_dcn_seconds(self) -> float:
        return self.dcn_bytes_per_step / (self.dcn_gbps * 1e9)

    def to_dict(self, top_ops: Optional[int] = None) -> dict:
        if top_ops is None:
            try:
                top_ops = int(os.environ.get(COMM_TOP_OPS_ENV, "16"))
            except ValueError:
                top_ops = 16
        verdict = detect_full_reshard(self)
        ranked = sorted(self.ops,
                        key=lambda o: o.dcn_bytes + o.ici_bytes,
                        reverse=True)
        return {
            "numSlices": self.num_slices,
            "dcnBytesPerStep": round(self.dcn_bytes_per_step, 1),
            "iciBytesPerStep": round(self.ici_bytes_per_step, 1),
            "collectivesPerStep": {
                link: self.collectives(link)
                for link in (LINK_DCN, LINK_ICI, LINK_LOCAL)},
            "byLinkOp": {f"{link}/{kind}": {
                "count": row["count"], "bytes": round(row["bytes"], 1),
                "phases": dict(sorted(row["phases"].items()))}
                for (link, kind), row in sorted(self.by_link_op().items())},
            "modeledSeconds": {
                "ici": self.modeled_ici_seconds,
                "dcn": self.modeled_dcn_seconds,
                "total": self.modeled_ici_seconds +
                self.modeled_dcn_seconds,
            },
            "bandwidthGBps": {"ici": self.ici_gbps, "dcn": self.dcn_gbps},
            "dcnFullReshard": verdict.to_dict(),
            "topOps": [o.to_dict() for o in ranked[:max(0, top_ops)]],
            "totalOps": len(self.ops),
        }


def _bw(env: str, default: float) -> float:
    raw = os.environ.get(env)
    if not raw:
        return default
    try:
        v = float(raw)
        if v > 0 and math.isfinite(v):
            return v
    except ValueError:
        pass
    # loud, but never fatal: the profile runs inside the worker's
    # first step, where a typo'd knob must cost the operator a warning
    # and a default-bandwidth model, not the training job
    import logging
    logging.getLogger(__name__).warning(
        "%s=%r is not a positive number; modeling at the default "
        "%g GB/s", env, raw, default)
    return default


def analyze_hlo(hlo_text: str, slice_of: Sequence[int],
                mesh_axes=None,
                ici_gbps: Optional[float] = None,
                dcn_gbps: Optional[float] = None) -> CommProfile:
    """Parse + classify one compiled module's collectives.

    ``slice_of`` maps participant id → slice id (``slice_assignment``);
    ``mesh_axes`` (optional ordered (name, size) pairs) labels each
    group with the mesh axes it spans."""
    ops = parse_hlo_collectives(hlo_text)
    for op in ops:
        _classify_op(op, slice_of, mesh_axes)
    return CommProfile(
        ops=ops,
        num_slices=len(set(slice_of)) or 1,
        ici_gbps=ici_gbps if ici_gbps else _bw(ICI_GBPS_ENV,
                                               DEFAULT_ICI_GBPS),
        dcn_gbps=dcn_gbps if dcn_gbps else _bw(DCN_GBPS_ENV,
                                               DEFAULT_DCN_GBPS))


def profile_step(compiled, mesh, num_slices: int,
                 ici_gbps: Optional[float] = None,
                 dcn_gbps: Optional[float] = None) -> CommProfile:
    """Convenience wrapper: profile a ``jax.stages.Compiled`` train step
    against its mesh + slice count (the worker / bench / dryrun entry)."""
    hlo = compiled.as_text() if hasattr(compiled, "as_text") \
        else str(compiled)
    return analyze_hlo(
        hlo, slice_assignment(mesh, num_slices),
        mesh_axes=[(a, int(mesh.shape[a])) for a in mesh.axis_names],
        ici_gbps=ici_gbps, dcn_gbps=dcn_gbps)


# ---------------------------------------------------------------------------
# worker metric export


class CommSeries:
    """Handle over the ``kftpu_comm_*`` series one profile exported, so
    the worker can prune them at job teardown (the kftpu_job_phase
    rule: a long-lived process must not export a finished job's comm
    profile forever). The labeled per-(link, op) series are removed
    outright; the unlabeled detector flag resets to 0 (unlabeled
    families render-zero by registry design)."""

    def __init__(self, bytes_fam, coll_fam, flag_fam, label_sets):
        self._bytes = bytes_fam
        self._coll = coll_fam
        self._flag = flag_fam
        self._label_sets = label_sets

    def prune(self) -> None:
        for kv in self._label_sets:
            self._bytes.remove(**kv)
            self._coll.remove(**kv)
        self._label_sets = []
        self._flag.set(0)


def export_comm_metrics(profile: CommProfile) -> CommSeries:
    """Export one profile as worker gauges:
    ``kftpu_comm_bytes_per_step{link,op}``,
    ``kftpu_comm_collectives_per_step{link,op}``, and
    ``kftpu_comm_dcn_full_reshard`` (0/1 — the structured verdict as a
    scrapeable red flag)."""
    from . import registry as obsreg
    bytes_fam = obsreg.gauge(
        "kftpu_comm_bytes_per_step",
        "modeled per-step collective bytes from the compiled train "
        "step's HLO, by link class and op kind (obs/collectives.py)",
        labels=("link", "op"))
    coll_fam = obsreg.gauge(
        "kftpu_comm_collectives_per_step",
        "collective ops per compiled train step, by link class and op "
        "kind",
        labels=("link", "op"))
    flag_fam = obsreg.gauge(
        "kftpu_comm_dcn_full_reshard",
        "1 when the compiled step carries an involuntary full-reshard "
        "across the DCN boundary (the MULTICHIP_r05 pathology)")
    label_sets = []
    for (link, kind), row in profile.by_link_op().items():
        kv = {"link": link, "op": kind}
        bytes_fam.labels(**kv).set(row["bytes"])
        coll_fam.labels(**kv).set(row["count"])
        label_sets.append(kv)
    flag_fam.set(1 if detect_full_reshard(profile).flagged else 0)
    return CommSeries(bytes_fam, coll_fam, flag_fam, label_sets)


# ---------------------------------------------------------------------------
# the full-reshard / involuntary-remat detector


@dataclass
class ReshardVerdict:
    """Structured verdict replacing the SPMD partitioner's
    "involuntary full rematerialization" log line nobody parses."""

    flagged: bool
    ops: list = field(default_factory=list)     # offending op dicts
    reason: str = ""

    def to_dict(self) -> dict:
        return {"flagged": self.flagged, "reason": self.reason,
                "ops": self.ops}


def detect_full_reshard(profile: CommProfile) -> ReshardVerdict:
    """Flag replicated-parameter reshards crossing the slice boundary —
    the MULTICHIP_r05 pathology, as a structured verdict.

    Rule (pinned against the live bad config by the dryrun and bench
    --mode comm): a DCN-crossing **all-gather or collective-permute**
    attributed OUTSIDE the weight-update region is a forward/backward
    re-layout paying the slow link every step — exactly what SPMD's
    "replicate the tensor and then partition it" last resort emits.
    Legitimate DCN traffic never matches: gradient reductions are
    all-reduce/reduce-scatter, the ZeRO-2 param re-gather carries
    update-region (trainstep.py) metadata, and pipeline stage
    send/recv (phase=pipeline: the GPipe ppermute in pipeline.py, any
    multislice stage transfer) is DELIBERATE activation traffic —
    skipped outright, a pipeline mesh spanning slices pays that hop by
    design. An op with no source metadata counts as model-region — an
    unattributed DCN reshard should flag, not hide."""
    offenders = [
        op for op in profile.ops
        if op.link == LINK_DCN
        and op.kind in ("all-gather", "collective-permute")
        and op.phase == PHASE_MODEL
    ]
    if not offenders:
        return ReshardVerdict(
            flagged=False,
            reason="no DCN-crossing reshard outside the weight-update "
                   "region")
    total = sum(op.dcn_bytes for op in offenders)
    return ReshardVerdict(
        flagged=True,
        ops=[op.to_dict() for op in offenders],
        reason=f"{len(offenders)} DCN-crossing reshard collective(s) in "
               f"the model forward/backward ({total:.0f} modeled DCN "
               f"bytes/step) — the SPMD involuntary-full-"
               f"rematerialization pathology")


# ---------------------------------------------------------------------------
# the optimizer-update yardstick (the ZeRO-2 decomposition)


def _merge_split_gathers(ops: list[CollectiveOp], hlo_text: str) -> list:
    """The CPU partitioner sometimes emits ONE logical param re-gather
    as TWO all-gathers combined by a single consumer
    (``add(all-gather(a), all-gather(b))`` — observed on the zero2
    arms). Payload-dedup by shape would wrongly collapse genuinely
    distinct same-shape leaves (8 LN scales), so the merge keys on the
    CONSUMER: gathers with identical payload + groups referenced
    together by one instruction count once."""
    by_name = {op.name: op for op in ops}
    if not by_name:
        return ops
    merged: set = set()
    name_re = re.compile(r"%([\w.\-]+)")
    for line in hlo_text.splitlines():
        if "=" not in line or "%" not in line:
            continue
        m = re.match(r"\s*(?:ROOT\s+)?%?[\w.\-]+\s*=", line)
        if not m:
            continue
        if _OP_RE.match(line):
            continue   # a collective consuming a collective: not a merge
        rhs = line.split("=", 1)[1]
        # an already-merged gather cannot anchor (or join) a further
        # merge — chaining through it would collapse DISTINCT logical
        # gathers that merely share a consumer with the merged one
        hits = [n for n in name_re.findall(rhs)
                if n in by_name and n not in merged]
        if len(hits) < 2:
            continue
        base = by_name[hits[0]]
        for other_name in hits[1:]:
            other = by_name[other_name]
            if (other.payload_bytes == base.payload_bytes
                    and other.groups == base.groups
                    and other.name not in merged
                    and other.name != base.name):
                merged.add(other.name)
    return [op for op in ops if op.name not in merged]


def modeled_update_dcn_bytes(profile: CommProfile,
                             hlo_text: str = "") -> dict:
    """Modeled optimizer-update DCN bytes/step — the yardstick the
    weight-update A/B is judged on.

    Total wire bytes are CONSERVED between the replicated and sharded
    updates (reduce-scatter + all-gather ≡ all-reduce on the wire);
    this metric isolates the update phase each scheme owns:

    - replicated: the reduced gradient must land back on EVERY replica
      because every replica runs the full update — the gradient
      all-reduce at its full factor-2 ring cost, ``2*G*(k-1)/k``.
    - sharded (ZeRO-2): the update phase owns only the final param
      re-gather, ``G*(k-1)/k`` (the reduce-scatter is gradient
      PRODUCTION — any DP scheme pays it).

    G comes from the measured op inventory (param-shaped payloads,
    split-gather pairs merged), so the number tracks the actual
    compiled program, and the factor-2 redundancy is the modeled part.
    """
    sharded_ops = [op for op in profile.ops
                   if op.kind == "reduce-scatter"
                   or (op.kind == "all-gather" and op.in_update_region)]
    if sharded_ops:
        gathers = [op for op in profile.ops
                   if op.kind == "all-gather" and op.in_update_region]
        if hlo_text:
            gathers = _merge_split_gathers(gathers, hlo_text)
        bytes_ = sum(op.payload_bytes * (op.slices_spanned - 1)
                     / op.slices_spanned
                     for op in gathers if op.slices_spanned > 1)
        param_bytes = sum(op.payload_bytes for op in gathers)
        return {"style": "sharded", "bytes": bytes_,
                "paramBytes": param_bytes}
    ars = [op for op in profile.ops
           if op.kind == "all-reduce"
           and any(dims for _, dims in op.result_shapes)]
    bytes_ = sum(2.0 * op.payload_bytes * (op.slices_spanned - 1)
                 / op.slices_spanned
                 for op in ars if op.slices_spanned > 1)
    return {"style": "replicated", "bytes": bytes_,
            "paramBytes": sum(op.payload_bytes for op in ars)}
