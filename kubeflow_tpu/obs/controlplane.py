"""Control-plane telemetry: audit accounting + pass-scoped profiling.

The ROADMAP's scale-out tier (incremental/sharded scheduling passes,
informer caches) needs plan-pass latency, apiserver write amplification,
and watch fan-out as gated PERF.md metrics before any of it can be
A/B'd. This module is that telemetry plane:

- **One vocabulary, defined once.** Verb, phase, relist-reason, and
  outcome literals live HERE and nowhere else (tests/test_lint.py pins
  it): the client-side audit, the FakeCluster's server-side audit, the
  REST apiserver, the scheduler's phase timers, and the dashboard all
  report through the same strings, so "client says N, server says M"
  is a real reconciliation, never a spelling drift.
- **`AuditingKubeClient`** — the ChaosKubeClient/RecordingKubeClient
  stacking pattern: wraps any KubeClient, counts every request per
  (verb, kind) under a fixed component name, estimates list payloads,
  and stamps the component into a contextvar so the SERVER side
  attributes the same call to the same component. The wrapper is its
  own exact bookkeeper (plain dicts) and mirrors into `kftpu_ctrl_*`
  registry counters via resolved-once children — the audit must cost
  <1% of a no-op pass (bench-asserted, the PR 5 bar).
- **`ServerAudit`** — the apiserver's own ledger (FakeCluster and the
  REST ClusterAPIServer both carry one): requests per (component, verb,
  kind), list object-counts/bytes, and watch fan-out (events delivered
  x matching watchers). Exact dicts are the bookkeeper; `export()`
  snapshot-bridges them into `kftpu_ctrl_server_*` counters (the
  registry's documented counter-set() bridge). `audit_mismatches()`
  asserts client totals reconcile EXACTLY against server totals —
  bench.py --mode ctrl-scale gates on an empty mismatch list.
- **`ctrl_pass()`** — a pass-scoped context (scheduler plan pass,
  controller process_one) that accumulates phase timings
  (snapshot/health-pass/plan/writes/warm-pass), per-pass request and
  write counts, and the pass's **write amplification** (mutating calls
  / distinct objects actually changed), then classifies the pass
  no-op vs write-bearing and emits a `ctrl-pass` span whose CHILD
  spans are the phases — a slow pass reconstructs phase-by-phase from
  the JSONL sink alone (obs/trace.py reconstruct). No-op passes are
  sampled 1-in-N (KFTPU_CTRL_SPAN_SAMPLE); write-bearing passes are
  NEVER sampled away (test-pinned) — a 10k-job soak must not write
  gigabytes of identical no-op spans.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from typing import Optional

from ..cluster.client import KubeClient, Watch
from . import registry as obsreg
from .trace import default_tracer, new_span_id

# ------------------------------------------------------------- vocabulary
# Defined ONCE here; every other module imports the constants. The
# literals below must not be respelled elsewhere (tests/test_lint.py).

VERB_CREATE = "create"
VERB_GET = "get"
VERB_LIST = "list"
VERB_UPDATE = "update"
VERB_UPDATE_STATUS = "update_status"
VERB_PATCH = "patch"
VERB_DELETE = "delete"
VERB_WATCH = "watch"
VERBS = (VERB_CREATE, VERB_GET, VERB_LIST, VERB_UPDATE, VERB_UPDATE_STATUS,
         VERB_PATCH, VERB_DELETE, VERB_WATCH)
#: verbs that (attempt to) change server state — the write-amplification
#: numerator; a pass issuing zero of these is a no-op pass
MUTATING_VERBS = frozenset((VERB_CREATE, VERB_UPDATE, VERB_UPDATE_STATUS,
                            VERB_PATCH, VERB_DELETE))

#: kind label for an unfiltered watch (no kind selector)
KIND_ANY = "*"

PHASE_SNAPSHOT = "snapshot"      # list/read + parse/validate loop
PHASE_HEALTH = "health-pass"     # node-health fold (scores, quarantines)
PHASE_PLAN = "plan"              # pure planning (carve_down + plan())
PHASE_WRITES = "writes"          # applying decisions (binds/preempts/...)
PHASE_WARM = "warm-pass"         # warm-pool advertisement/reconcile
PHASES = (PHASE_SNAPSHOT, PHASE_HEALTH, PHASE_PLAN, PHASE_WRITES,
          PHASE_WARM)

RELIST_INITIAL = "initial"       # informer initial sync (Manager.add)
RELIST_RESYNC = "resync"         # periodic SyncPeriod relist
RELIST_LEADER_GAIN = "leader-gain"  # adopt-the-world on gaining the lease
RELIST_REASONS = (RELIST_INITIAL, RELIST_RESYNC, RELIST_LEADER_GAIN)

OUTCOME_NOOP = "noop"
OUTCOME_WRITE = "write"

#: requests whose caller did not come through an AuditingKubeClient
#: (test hand-of-god helpers, unaudited components)
UNATTRIBUTED = "unattributed"

#: REST header carrying the caller's component name (cluster/apiserver.py
#: adopts it for the request's server-side attribution)
COMPONENT_HEADER = "X-Kftpu-Component"

CTRL_PASS_SPAN = "ctrl-pass"
#: trace-id prefix for pass spans: each emitted pass is its own trace so
#: reconstruct(path, trace_id) rebuilds exactly one pass phase-by-phase
CTRL_PASS_TRACE_PREFIX = "ctrlpass-"

#: no-op-pass span sampling: emit 1-in-N no-op ctrl-pass spans per
#: component (write-bearing passes always emit). <=1 emits everything.
CTRL_SPAN_SAMPLE_ENV = "KFTPU_CTRL_SPAN_SAMPLE"
CTRL_SPAN_SAMPLE_DEFAULT = 10


# ---------------------------------------------------- component attribution

# The request-scoped component: AuditingKubeClient sets it for the
# duration of each inner call, so the SERVER side (FakeCluster CRUD, the
# REST handler) attributes the request to the same component the client
# side counted it under — that agreement is what makes the
# client-vs-server reconciliation exact instead of approximate.
_component: contextvars.ContextVar = contextvars.ContextVar(
    "kftpu_ctrl_component", default=UNATTRIBUTED)

# The active pass (ctrl_pass), if any — audited calls report into it so
# a pass knows its own reads/writes/objects-changed without the
# reconciler threading a context through every call site.
_active_pass: contextvars.ContextVar = contextvars.ContextVar(
    "kftpu_ctrl_pass", default=None)


def current_component() -> str:
    """The component the in-flight request is attributed to."""
    return _component.get()


@contextlib.contextmanager
def attributed(component: str):
    """Attribute server-side accounting to ``component`` for the block
    (what AuditingKubeClient does per call; exposed for drivers that
    must attribute hand-of-god helpers like FakeCluster.tick())."""
    token = _component.set(component)
    try:
        yield
    finally:
        _component.reset(token)


def payload_bytes(objs: list) -> int:
    """Deterministic list-payload estimate: serialized size of the FIRST
    object x count. Exact JSON of a 10k-object list would cost more than
    the pass it measures; the first-object sample is cheap, stable, and
    — computed from identical content on both sides of the wire — lands
    on the SAME number client- and server-side, so byte totals reconcile
    exactly too."""
    if not objs:
        return 0
    return len(json.dumps(objs[0], sort_keys=True,
                          separators=(",", ":"))) * len(objs)


# ------------------------------------------------------- server-side audit

class ServerAudit:
    """The apiserver's own request ledger (FakeCluster and the REST
    ClusterAPIServer each carry one). Plain dicts under one lock are the
    exact bookkeeper; ``export()`` snapshot-bridges them into
    ``kftpu_ctrl_server_*`` counters."""

    def __init__(self):
        self._lock = threading.Lock()
        #: (component, verb, kind) -> request count
        self.requests: dict[tuple, int] = {}
        #: (component, kind) -> objects returned by list
        self.list_objects: dict[tuple, int] = {}
        #: (component, kind) -> estimated list payload bytes
        self.list_bytes: dict[tuple, int] = {}
        #: kind -> mutation events broadcast to the watch plane
        self.watch_broadcasts: dict[str, int] = {}
        #: kind -> event copies delivered (events x matching watchers)
        self.watch_delivered: dict[str, int] = {}

    def record(self, verb: str, kind: str, *, objects: Optional[int] = None,
               nbytes: Optional[int] = None) -> None:
        comp = _component.get()
        with self._lock:
            key = (comp, verb, kind)
            self.requests[key] = self.requests.get(key, 0) + 1
            if objects is not None:
                lk = (comp, kind)
                self.list_objects[lk] = self.list_objects.get(lk, 0) + objects
                self.list_bytes[lk] = self.list_bytes.get(lk, 0) + (nbytes or 0)

    def record_broadcast(self, kind: str, delivered: int) -> None:
        with self._lock:
            self.watch_broadcasts[kind] = self.watch_broadcasts.get(kind, 0) + 1
            self.watch_delivered[kind] = \
                self.watch_delivered.get(kind, 0) + delivered

    def record_delivered(self, kind: str, n: int = 1) -> None:
        """Deliveries without a broadcast event of their own — the REST
        watch streams (each stream is one watcher; the backing
        FakeCluster already counted the broadcast)."""
        with self._lock:
            self.watch_delivered[kind] = \
                self.watch_delivered.get(kind, 0) + n

    def totals(self) -> dict:
        """Snapshot for reconciliation/export (keys copied, safe to hold)."""
        with self._lock:
            return {"requests": dict(self.requests),
                    "list_objects": dict(self.list_objects),
                    "list_bytes": dict(self.list_bytes),
                    "watch_broadcasts": dict(self.watch_broadcasts),
                    "watch_delivered": dict(self.watch_delivered)}

    def fanout(self, kind: Optional[str] = None) -> float:
        """Mean watch fan-out (delivered copies per broadcast event),
        overall or for one kind."""
        t = self.totals()
        if kind is None:
            b = sum(t["watch_broadcasts"].values())
            d = sum(t["watch_delivered"].values())
        else:
            b = t["watch_broadcasts"].get(kind, 0)
            d = t["watch_delivered"].get(kind, 0)
        return d / b if b else 0.0

    def export(self, registry: Optional[obsreg.Registry] = None) -> None:
        """Snapshot-bridge the ledger into the registry (counter.set()
        is the documented bridge for sources keeping their own monotonic
        totals). Called on scrape/bench boundaries, not per request."""
        reg = registry or obsreg.default_registry()
        t = self.totals()
        req = reg.counter("kftpu_ctrl_server_requests_total",
                          "apiserver-side requests per component/verb/kind",
                          labels=("component", "verb", "kind"))
        for (comp, verb, kind), n in t["requests"].items():
            req.labels(component=comp, verb=verb, kind=kind).set(n)
        lo = reg.counter("kftpu_ctrl_server_list_objects_total",
                         "objects returned by list, server-side",
                         labels=("component", "kind"))
        lb = reg.counter("kftpu_ctrl_server_list_bytes_total",
                         "estimated list payload bytes, server-side",
                         labels=("component", "kind"))
        for (comp, kind), n in t["list_objects"].items():
            lo.labels(component=comp, kind=kind).set(n)
        for (comp, kind), n in t["list_bytes"].items():
            lb.labels(component=comp, kind=kind).set(n)
        wb = reg.counter("kftpu_ctrl_watch_broadcasts_total",
                         "mutation events broadcast to the watch plane",
                         labels=("kind",))
        wd = reg.counter("kftpu_ctrl_watch_events_delivered_total",
                         "watch event copies delivered "
                         "(events x matching watchers)", labels=("kind",))
        wf = reg.gauge("kftpu_ctrl_watch_fanout",
                       "mean delivered copies per broadcast event",
                       labels=("kind",))
        for kind, n in t["watch_broadcasts"].items():
            wb.labels(kind=kind).set(n)
            wf.labels(kind=kind).set(round(
                t["watch_delivered"].get(kind, 0) / n, 6) if n else 0.0)
        for kind, n in t["watch_delivered"].items():
            wd.labels(kind=kind).set(n)


# ------------------------------------------------------- client-side audit

class AuditingKubeClient(KubeClient):
    """Counts every request this component issues, per (verb, kind) —
    the stacking-wrapper pattern (ChaosKubeClient, RecordingKubeClient):
    wraps any inner KubeClient, passes unknown attributes through
    (FakeCluster test helpers keep working), and stamps its component
    into the attribution contextvar around each call so the server's
    ledger agrees with this one. Stacks both ways: audit-over-chaos
    counts what the component TRIED (injected faults included);
    chaos-over-audit counts what reached the server."""

    def __init__(self, inner: KubeClient, component: str):
        self.inner = inner
        self.component = component
        # cross-process attribution: an HTTP inner carries the component
        # in a request header, so a remote apiserver's ServerAudit rows
        # reconcile against this client exactly like FakeCluster's do.
        hdrs = getattr(inner, "_headers", None)
        if isinstance(hdrs, dict):
            hdrs[COMPONENT_HEADER] = component
        self._lock = threading.Lock()
        #: (verb, kind) -> requests issued
        self.requests: dict[tuple, int] = {}
        #: kind -> objects received from list
        self.list_objects: dict[str, int] = {}
        #: kind -> estimated list payload bytes received
        self.list_bytes: dict[str, int] = {}
        # resolved-once registry children, keyed (verb, kind) — the
        # hot-path rule: no label hashing per request
        self._req_children: dict[tuple, object] = {}
        self._list_children: dict[str, tuple] = {}

    def __getattr__(self, name: str):
        return getattr(self.inner, name)

    def totals(self) -> dict:
        with self._lock:
            return {"requests": dict(self.requests),
                    "list_objects": dict(self.list_objects),
                    "list_bytes": dict(self.list_bytes)}

    # -- accounting ---------------------------------------------------------

    def _note(self, verb: str, kind: str, *, ok: bool,
              changed_key: Optional[tuple] = None,
              objects: Optional[int] = None,
              nbytes: Optional[int] = None) -> None:
        with self._lock:
            key = (verb, kind)
            self.requests[key] = self.requests.get(key, 0) + 1
            if objects is not None:
                self.list_objects[kind] = \
                    self.list_objects.get(kind, 0) + objects
                self.list_bytes[kind] = \
                    self.list_bytes.get(kind, 0) + (nbytes or 0)
            child = self._req_children.get(key)
            if child is None:
                child = obsreg.counter(
                    "kftpu_ctrl_requests_total",
                    "control-plane requests issued per "
                    "component/verb/kind", labels=("component", "verb",
                                                   "kind")).labels(
                        component=self.component, verb=verb, kind=kind)
                self._req_children[key] = child
        child.inc()
        if objects is not None:
            pair = self._list_children.get(kind)
            if pair is None:
                pair = (
                    obsreg.counter(
                        "kftpu_ctrl_list_objects_total",
                        "objects received from list per component/kind",
                        labels=("component", "kind")).labels(
                            component=self.component, kind=kind),
                    obsreg.counter(
                        "kftpu_ctrl_list_bytes_total",
                        "estimated list payload bytes received",
                        labels=("component", "kind")).labels(
                            component=self.component, kind=kind))
                with self._lock:
                    self._list_children[kind] = pair
            pair[0].inc(objects)
            pair[1].inc(nbytes or 0)
        ctx = _active_pass.get()
        if ctx is not None:
            ctx.note_request(verb, kind, ok=ok, changed_key=changed_key)

    @contextlib.contextmanager
    def _call(self, verb: str, kind: str,
              changed_key: Optional[tuple] = None):
        """Attribute + count one inner call; failures count too (the
        server processed the request either way, so both ledgers move)."""
        token = _component.set(self.component)
        ok = True
        try:
            yield
        except BaseException:
            ok = False
            raise
        finally:
            _component.reset(token)
            self._note(verb, kind, ok=ok,
                       changed_key=changed_key if ok else None)

    # -- the KubeClient surface ---------------------------------------------

    @staticmethod
    def _obj_key(obj: dict) -> tuple:
        meta = obj.get("metadata", {}) or {}
        return (obj.get("kind", ""), meta.get("namespace", ""),
                meta.get("name", ""))

    def create(self, obj: dict) -> dict:
        with self._call(VERB_CREATE, obj.get("kind", ""),
                        changed_key=self._obj_key(obj)):
            return self.inner.create(obj)

    def get(self, api_version: str, kind: str, namespace: str,
            name: str) -> dict:
        with self._call(VERB_GET, kind):
            return self.inner.get(api_version, kind, namespace, name)

    def list(self, api_version: str, kind: str,
             namespace: Optional[str] = None,
             selector: Optional[dict] = None) -> list[dict]:
        token = _component.set(self.component)
        ok = True
        try:
            out = self.inner.list(api_version, kind, namespace=namespace,
                                  selector=selector)
        except BaseException:
            ok = False
            out = None
            raise
        finally:
            _component.reset(token)
            self._note(VERB_LIST, kind, ok=ok,
                       objects=len(out) if ok else 0,
                       nbytes=payload_bytes(out) if ok else 0)
        return out

    def update(self, obj: dict) -> dict:
        with self._call(VERB_UPDATE, obj.get("kind", ""),
                        changed_key=self._obj_key(obj)):
            return self.inner.update(obj)

    def update_status(self, obj: dict) -> dict:
        with self._call(VERB_UPDATE_STATUS, obj.get("kind", ""),
                        changed_key=self._obj_key(obj)):
            return self.inner.update_status(obj)

    def patch(self, api_version: str, kind: str, namespace: str, name: str,
              patch: dict) -> dict:
        with self._call(VERB_PATCH, kind,
                        changed_key=(kind, namespace, name)):
            return self.inner.patch(api_version, kind, namespace, name,
                                    patch)

    def delete(self, api_version: str, kind: str, namespace: str, name: str,
               cascade: bool = True) -> None:
        with self._call(VERB_DELETE, kind,
                        changed_key=(kind, namespace, name)):
            return self.inner.delete(api_version, kind, namespace, name,
                                     cascade=cascade)

    def watch(self, api_version: Optional[str] = None,
              kind: Optional[str] = None) -> Watch:
        with self._call(VERB_WATCH, kind or KIND_ANY):
            return self.inner.watch(api_version, kind)


def audit_mismatches(clients: dict[str, AuditingKubeClient],
                     server: ServerAudit) -> list[str]:
    """Exact reconciliation: for every audited component, the client's
    per-(verb, kind) request counts and per-kind list object/byte totals
    must EQUAL the server ledger's rows for that component — both
    directions (a server row for an audited component with no client
    counterpart is a mismatch too). Returns human-readable mismatch
    lines; empty list == the accounting is exact. Server rows for
    components outside ``clients`` (unattributed hand-of-god helpers)
    are ignored — they have no client ledger to reconcile against."""
    out: list[str] = []
    st = server.totals()
    for comp, client in clients.items():
        ct = client.totals()
        server_req = {(v, k): n for (c, v, k), n in st["requests"].items()
                      if c == comp}
        for vk in sorted(set(ct["requests"]) | set(server_req)):
            a, b = ct["requests"].get(vk, 0), server_req.get(vk, 0)
            if a != b:
                out.append(f"{comp} {vk[0]}/{vk[1]}: client={a} server={b}")
        for field in ("list_objects", "list_bytes"):
            server_rows = {k: n for (c, k), n in st[field].items()
                           if c == comp}
            for kind in sorted(set(ct[field]) | set(server_rows)):
                a, b = ct[field].get(kind, 0), server_rows.get(kind, 0)
                if a != b:
                    out.append(f"{comp} {field}/{kind}: "
                               f"client={a} server={b}")
    return out


# -------------------------------------------------------- pass-scoped audit

class PassContext:
    """Accounting for ONE reconcile/plan pass: phase timings, request
    and write counts, distinct objects changed. Created by ctrl_pass();
    audited clients report into it via the contextvar."""

    def __init__(self, component: str):
        self.component = component
        self.started = time.time()
        #: phase -> [accumulated seconds, first wall start, last wall end]
        self.phases: dict[str, list] = {}
        #: (verb, kind) -> requests within this pass
        self.requests: dict[tuple, int] = {}
        self.mutating_calls = 0
        #: distinct (kind, ns, name) successfully changed
        self.changed: set = set()
        #: free-form span attributes (jobs scanned, key, ...)
        self.attrs: dict = {}

    def note(self, **attrs) -> None:
        """Attach pass-level attributes (land on the ctrl-pass span)."""
        self.attrs.update(attrs)

    def note_request(self, verb: str, kind: str, *, ok: bool,
                     changed_key: Optional[tuple] = None) -> None:
        key = (verb, kind)
        self.requests[key] = self.requests.get(key, 0) + 1
        if verb in MUTATING_VERBS:
            self.mutating_calls += 1
            if ok and changed_key is not None:
                self.changed.add(changed_key)

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time one phase; re-entry ACCUMULATES (the writes phase runs
        per decision, interleaved) and the child span spans first start
        to last end."""
        if name not in PHASES:
            raise ValueError(f"unknown ctrl phase {name!r}; "
                             f"vocabulary: {PHASES}")
        t0 = time.perf_counter()
        w0 = time.time()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            rec = self.phases.get(name)
            if rec is None:
                self.phases[name] = [dt, w0, time.time()]
            else:
                rec[0] += dt
                rec[2] = time.time()

    @property
    def wrote(self) -> bool:
        return self.mutating_calls > 0

    @property
    def write_amplification(self) -> float:
        """Mutating calls issued / distinct objects actually changed.
        1.0 is the floor for a write-bearing pass; conflict retries and
        repeated patches to one object push it up. 0.0 for no-op
        passes (no writes to amplify)."""
        if not self.mutating_calls:
            return 0.0
        return self.mutating_calls / max(1, len(self.changed))


# no-op span sampling state: per-component pass counters (deterministic,
# not random — 1-in-N means exactly every Nth no-op pass emits)
_sample_lock = threading.Lock()
_noop_counts: dict[str, int] = {}


def reset_span_sampling() -> None:
    """Zero the per-component no-op sampling counters (test seam)."""
    with _sample_lock:
        _noop_counts.clear()


def _sample_n() -> int:
    try:
        n = int(os.environ.get(CTRL_SPAN_SAMPLE_ENV) or
                CTRL_SPAN_SAMPLE_DEFAULT)
    except ValueError:
        n = CTRL_SPAN_SAMPLE_DEFAULT
    return max(1, n)


def _should_emit(component: str, wrote: bool) -> bool:
    # write-bearing passes are NEVER sampled away: the span is the only
    # per-pass record tying writes to their phase timings
    if wrote:
        return True
    n = _sample_n()
    with _sample_lock:
        c = _noop_counts.get(component, 0)
        _noop_counts[component] = c + 1
    return c % n == 0


def _finish_pass(ctx: PassContext, duration: float) -> None:
    comp = ctx.component
    outcome = OUTCOME_WRITE if ctx.wrote else OUTCOME_NOOP
    obsreg.counter(
        "kftpu_ctrl_passes_total",
        "reconcile/plan passes by outcome (no-op-pass ratio = "
        "noop / total)", labels=("component", "outcome")).labels(
            component=comp, outcome=outcome).inc()
    obsreg.histogram(
        "kftpu_ctrl_pass_seconds", "wall time of one pass",
        labels=("component",)).labels(component=comp).observe(duration)
    phase_h = obsreg.histogram(
        "kftpu_ctrl_pass_phase_seconds",
        "per-phase wall time within one pass",
        labels=("component", "phase"))
    for name, (sec, _w0, _w1) in ctx.phases.items():
        phase_h.labels(component=comp, phase=name).observe(sec)
    if ctx.wrote:
        obsreg.counter(
            "kftpu_ctrl_pass_writes_total",
            "mutating calls issued by passes",
            labels=("component",)).labels(component=comp).inc(
                ctx.mutating_calls)
        obsreg.counter(
            "kftpu_ctrl_pass_objects_changed_total",
            "distinct objects actually changed by passes",
            labels=("component",)).labels(component=comp).inc(
                len(ctx.changed))
        obsreg.gauge(
            "kftpu_ctrl_write_amplification",
            "last write-bearing pass: mutating calls / distinct "
            "objects changed", labels=("component",)).labels(
                component=comp).set(round(ctx.write_amplification, 6))
    if not _should_emit(comp, ctx.wrote):
        return
    tracer = default_tracer(comp)
    if tracer is None:
        return
    span_id = new_span_id()
    trace_id = CTRL_PASS_TRACE_PREFIX + span_id
    attrs = dict(ctx.attrs)
    attrs.update(component=comp, outcome=outcome,
                 requests=sum(ctx.requests.values()),
                 writes=ctx.mutating_calls,
                 objects_changed=len(ctx.changed))
    if ctx.wrote:
        attrs["write_amplification"] = round(ctx.write_amplification, 4)
    else:
        attrs["sample_n"] = _sample_n()
    end = ctx.started + duration
    tracer.emit(CTRL_PASS_SPAN, start=ctx.started, end=end,
                trace_id=trace_id, span_id=span_id, **attrs)
    # phases as CHILD spans, first-start order: reconstruct(path,
    # trace_id) rebuilds the pass timeline from the JSONL alone
    for name, (sec, w0, w1) in sorted(ctx.phases.items(),
                                      key=lambda kv: kv[1][1]):
        tracer.emit(name, start=w0, end=w1, trace_id=trace_id,
                    parent_id=span_id, seconds=round(sec, 6))


@contextlib.contextmanager
def ctrl_pass(component: str, **attrs):
    """Scope one reconcile/plan pass. Reentrant: a reconciler that opens
    its own pass while the controller runtime already opened one (the
    SliceScheduler under a Controller) joins the ACTIVE context instead
    of double-counting the pass."""
    active = _active_pass.get()
    if active is not None:
        active.attrs.update(attrs)
        yield active
        return
    ctx = PassContext(component)
    ctx.attrs.update(attrs)
    tok_c = _component.set(component)
    tok_p = _active_pass.set(ctx)
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        duration = time.perf_counter() - t0
        _active_pass.reset(tok_p)
        _component.reset(tok_c)
        _finish_pass(ctx, duration)


def record_relist(component: str, reason: str, objects: int) -> None:
    """Account one full relist (initial sync / periodic resync /
    leadership gain) — the list-storm signal the scale-out tier's
    informer caches are meant to flatten."""
    if reason not in RELIST_REASONS:
        raise ValueError(f"unknown relist reason {reason!r}; "
                         f"vocabulary: {RELIST_REASONS}")
    labels = ("component", "reason")
    obsreg.counter(
        "kftpu_ctrl_relists_total", "full relists by reason",
        labels=labels).labels(component=component, reason=reason).inc()
    obsreg.counter(
        "kftpu_ctrl_relist_objects_total",
        "objects re-listed (and re-enqueued) by relists",
        labels=labels).labels(component=component, reason=reason).inc(
            max(0, int(objects)))


def workqueue_dwell_histogram(component: str):
    """Resolved child for the workqueue dwell histogram (enqueue→pop
    latency per key) — resolved once per controller, held (hot-path
    rule)."""
    return obsreg.histogram(
        "kftpu_ctrl_workqueue_dwell_seconds",
        "enqueue-to-pop dwell per workqueue key",
        labels=("component",)).labels(component=component)


# ----------------------------------------------------------------- reading

def quantile_from_buckets(buckets: dict, q: float) -> float:
    """Prometheus-style histogram quantile from cumulative bucket counts
    (the _Child.bucket_counts() shape): linear interpolation within the
    bucket containing the rank; the +Inf bucket clamps to the largest
    finite bound."""
    import math
    total = buckets.get(math.inf, 0)
    if total <= 0:
        return 0.0
    rank = q * total
    prev_le, prev_n = 0.0, 0
    finite = sorted(b for b in buckets if b != math.inf)
    for le in finite:
        n = buckets[le]
        if n >= rank:
            if n == prev_n:
                return le
            return prev_le + (le - prev_le) * (rank - prev_n) / (n - prev_n)
        prev_le, prev_n = le, n
    return finite[-1] if finite else 0.0


def pass_stats(registry: Optional[obsreg.Registry] = None) -> dict:
    """Per-component pass statistics from the registry (the dashboard's
    /api/obs/controlplane payload): pass counts by outcome, no-op
    fraction, p50/p99 pass latency, write amplification, relists."""
    reg = registry or obsreg.default_registry()
    out: dict[str, dict] = {}

    def row(comp: str) -> dict:
        return out.setdefault(comp, {
            "passes": 0, "noopPasses": 0, "noopFraction": 0.0,
            "p50Seconds": 0.0, "p99Seconds": 0.0,
            "writeAmplification": 0.0, "relists": 0,
            "relistObjects": 0})

    fam = reg.family("kftpu_ctrl_passes_total")
    for key, child in (fam.children().items() if fam else ()):
        comp, outcome = key
        r = row(comp)
        n = int(child.value)
        r["passes"] += n
        if outcome == OUTCOME_NOOP:
            r["noopPasses"] += n
    fam = reg.family("kftpu_ctrl_pass_seconds")
    for key, child in (fam.children().items() if fam else ()):
        r = row(key[0])
        b = child.bucket_counts()
        r["p50Seconds"] = round(quantile_from_buckets(b, 0.50), 6)
        r["p99Seconds"] = round(quantile_from_buckets(b, 0.99), 6)
    fam = reg.family("kftpu_ctrl_write_amplification")
    for key, child in (fam.children().items() if fam else ()):
        row(key[0])["writeAmplification"] = round(child.value, 4)
    fam = reg.family("kftpu_ctrl_relists_total")
    for key, child in (fam.children().items() if fam else ()):
        row(key[0])["relists"] += int(child.value)
    fam = reg.family("kftpu_ctrl_relist_objects_total")
    for key, child in (fam.children().items() if fam else ()):
        row(key[0])["relistObjects"] += int(child.value)
    for r in out.values():
        if r["passes"]:
            r["noopFraction"] = round(r["noopPasses"] / r["passes"], 4)
    return out
