"""The scrape surface: ``/metrics`` (+``/healthz``) over a registry.

Every long-running process grows the same two endpoints the serving
stack already had: the controller manager and the scheduler via
``python -m kubeflow_tpu.controllers --metrics-port``, workers via
``spec.observability.metricsPort``, probers via the support
MetricsServer. Components can mount extra endpoints through
``handlers`` — the worker uses this for the on-demand profiler trigger
(``POST /profile?steps=N``) and the flight-recorder peek
(``GET /flightrecorder``) without growing a second HTTP stack. stdlib
only — mirrors webapps/_http.py's threaded-server lifecycle without
making the base ``obs`` layer depend on webapps.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .registry import Registry, default_registry

# a mounted endpoint: (method, path) -> callable(query: dict) returning
# (status_code, json-serializable body)
Handler = Callable[[dict], tuple]


class ObsServer:
    """Serves ``registry.render()`` on ``/metrics``, a liveness
    ``/healthz``, and any mounted ``handlers``; daemon thread, ephemeral
    port when ``port=0``."""

    def __init__(self, registry: Optional[Registry] = None,
                 host: str = "0.0.0.0", port: int = 0,
                 name: str = "obs-metrics",
                 handlers: Optional[dict] = None):
        self.registry = registry if registry is not None \
            else default_registry()
        self.name = name
        registry_ref = self.registry
        handlers_ref = dict(handlers or {})

        class RequestHandler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _dispatch(self, method: str) -> None:
                path, _, rawq = self.path.partition("?")
                path = path.rstrip("/")
                handler = handlers_ref.get((method, path))
                if handler is None:
                    self._send(404, b"not found", "text/plain")
                    return
                query = {k: v[0] for k, v in
                         urllib.parse.parse_qs(rawq).items()}
                try:
                    code, body = handler(query)
                except Exception as e:  # noqa: BLE001 — a handler bug
                    # must not kill the scrape surface's server thread
                    code, body = 500, {"error": f"{type(e).__name__}: {e}"}
                self._send(code, json.dumps(body).encode(),
                           "application/json")

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/metrics":
                    self._send(200, registry_ref.render().encode(),
                               "text/plain; version=0.0.4")
                elif path in ("/healthz", ""):
                    self._send(200, b'{"ok": true}', "application/json")
                else:
                    self._dispatch("GET")

            def do_POST(self):
                # drain any body so keep-alive connections stay in sync;
                # handler inputs ride the query string
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    self.rfile.read(length)
                self._dispatch("POST")

        self._httpd = ThreadingHTTPServer((host, port), RequestHandler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name=self.name)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
