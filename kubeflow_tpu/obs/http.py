"""The scrape surface: ``/metrics`` (+``/healthz``) over a registry.

Every long-running process grows the same two endpoints the serving
stack already had: the controller manager and the scheduler via
``python -m kubeflow_tpu.controllers --metrics-port``, workers via
``spec.observability.metricsPort``, probers via the support
MetricsServer. stdlib only — mirrors webapps/_http.py's threaded-server
lifecycle without making the base ``obs`` layer depend on webapps.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .registry import Registry, default_registry


class ObsServer:
    """Serves ``registry.render()`` on ``/metrics`` and a liveness
    ``/healthz``; daemon thread, ephemeral port when ``port=0``."""

    def __init__(self, registry: Optional[Registry] = None,
                 host: str = "0.0.0.0", port: int = 0,
                 name: str = "obs-metrics"):
        self.registry = registry if registry is not None \
            else default_registry()
        self.name = name
        registry_ref = self.registry

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/metrics":
                    self._send(200, registry_ref.render().encode(),
                               "text/plain; version=0.0.4")
                elif path in ("/healthz", ""):
                    self._send(200, b'{"ok": true}', "application/json")
                else:
                    self._send(404, b"not found", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name=self.name)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
