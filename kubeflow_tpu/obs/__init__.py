"""Unified observability layer: shared metrics registry, trace spans,
and the ``/metrics`` scrape surface (ISSUE 5).

- ``obs.registry`` — dependency-free Prometheus-text Counter / Gauge /
  Histogram families; a process-wide default registry every in-process
  component instruments.
- ``obs.trace`` — one trace id per TPUJob, propagated annotation → env
  → worker, with every component appending JSONL spans to a shared sink
  so a job's queued → bound → running → windows → done timeline
  reconstructs end to end.
- ``obs.http`` — ``/metrics`` + ``/healthz`` over a registry.

jax-free and stdlib-only: the scheduler and operator processes import
this without pulling the runtime in.
"""

from .registry import (DEFAULT_BUCKETS, OBS_DISABLE_ENV,  # noqa: F401
                       Registry, counter, default_registry, gauge,
                       histogram, reset_default_registry)
from .trace import (SPAN_PATH_ENV, TRACE_ID_ANNOTATION,  # noqa: F401
                    TRACE_ID_ENV, SpanWriter, default_tracer, load_spans,
                    mint_trace_id, reconstruct, reset_default_tracers)
from .http import ObsServer  # noqa: F401
