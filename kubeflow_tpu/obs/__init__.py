"""Unified observability layer: shared metrics registry, trace spans,
and the ``/metrics`` scrape surface (ISSUE 5).

- ``obs.registry`` — dependency-free Prometheus-text Counter / Gauge /
  Histogram families; a process-wide default registry every in-process
  component instruments.
- ``obs.trace`` — one trace id per TPUJob, propagated annotation → env
  → worker, with every component appending JSONL spans to a shared sink
  so a job's queued → bound → running → windows → done timeline
  reconstructs end to end.
- ``obs.http`` — ``/metrics`` + ``/healthz`` over a registry, with
  mountable extra endpoints (the worker's profiler trigger).
- ``obs.goodput`` — the goodput ledger (ISSUE 10): the span stream
  folded into a per-job wall-clock decomposition, goodput vs named
  badput categories; the one category vocabulary the ledger, sim, and
  dashboard all share.

jax-free and stdlib-only: the scheduler and operator processes import
this without pulling the runtime in.
"""

from .registry import (DEFAULT_BUCKETS, OBS_DISABLE_ENV,  # noqa: F401
                       Registry, counter, default_registry, gauge,
                       histogram, reset_default_registry)
from .trace import (SPAN_MAX_BYTES_ENV, SPAN_PATH_ENV,  # noqa: F401
                    TRACE_ID_ANNOTATION, TRACE_ID_ENV, SpanWriter,
                    adopt_trace_env, default_tracer, load_spans,
                    mint_trace_id, reconstruct, reset_default_tracers)
from .http import ObsServer  # noqa: F401
from .goodput import (BADPUT_CATEGORIES, GOODPUT,  # noqa: F401
                      GOODPUT_ANNOTATION, categories_sum_ok,
                      cluster_rollup, decompose, export_job_ledger,
                      ledger_for)
