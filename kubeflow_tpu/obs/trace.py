"""Cross-layer trace spans: one trace id per TPUJob, JSONL span records.

The scaling wins Podracer (arXiv:2104.06272) and TF-Replicator
(arXiv:1902.00465) attribute to per-stage accounting need the stages
STITCHED: a job's queue wait, its pod start, its first step, and every
training window must reconstruct as ONE timeline. The contract:

- A ``trace_id`` is minted the first time the control plane touches a
  TPUJob (scheduler pass or operator reconcile — whichever sees it
  first) and persisted as the ``observability.kubeflow.org/trace-id``
  annotation, so every later actor agrees on it.
- The operator renders it into every worker pod as ``KFTPU_TRACE_ID``
  (next to the pod-identity env), and forwards its own
  ``KFTPU_SPAN_PATH`` so workers write spans where the operator does.
- Every component appends span records to that JSONL sink:
  ``{"trace_id", "span_id", "parent_id", "name", "component",
  "start", "end", "attrs"}`` — wall-clock seconds, so spans from
  different processes order on one axis. Point events (queued, bound,
  running) are zero-duration spans.
- ``reconstruct()`` reads the sink back into the end-to-end timeline:
  queued → bound → pod-start → running → windows → done. The dashboard
  serves it at ``/api/obs/jobs/<ns>/<name>``; tests and ``bench.py
  --mode obs`` assert on it.

Writers are append-only and line-atomic (one ``write()`` per record), so
scheduler, operator, and in-process workers can share a sink file the
way the chaos/scheduler soaks share a FakeCluster. jax-free, stdlib
only; the jax.profiler capture (``runtime/metrics.py profile_trace``)
hooks in as a child span around its start/stop.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from typing import Optional

# env contract (rendered by controllers/tpujob.py into worker pods;
# tests/test_lint.py pins the plumbing)
TRACE_ID_ENV = "KFTPU_TRACE_ID"
SPAN_PATH_ENV = "KFTPU_SPAN_PATH"
# sink size cap: at this many bytes the active JSONL rotates to
# ``<path>.1`` (one generation — long soaks previously grew the sink
# unbounded). 0/unset = no rotation.
SPAN_MAX_BYTES_ENV = "KFTPU_SPAN_MAX_BYTES"

# where the minted trace id persists on the job object (the one value
# every component — scheduler, operator, worker, dashboard — agrees on)
TRACE_ID_ANNOTATION = "observability.kubeflow.org/trace-id"


def mint_trace_id(uid: str = "") -> str:
    """A fresh trace id — DERIVED from the object's uid when one exists,
    so concurrent minters (the scheduler pass and the operator both
    waking on the same ADDED event) compute the SAME id and neither
    side's early spans are orphaned by a lost patch race."""
    if uid:
        import hashlib
        return hashlib.sha1(uid.encode()).hexdigest()[:16]
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


# per-path rotation locks: several SpanWriter instances in ONE process
# (operator + scheduler default tracers, the worker's tracer + its
# dedicated dump writer) share a sink — their rotations must serialize
_rotate_locks: dict = {}
_rotate_locks_guard = threading.Lock()


def _rotate_lock(path: str) -> threading.Lock:
    key = os.path.abspath(path)
    with _rotate_locks_guard:
        lock = _rotate_locks.get(key)
        if lock is None:
            lock = _rotate_locks[key] = threading.Lock()
        return lock


class _SpanCtx:
    """Context manager for a timed span; emits on exit (errors included —
    a failed phase's duration is still its duration)."""

    def __init__(self, writer: "SpanWriter", name: str,
                 trace_id: Optional[str], parent_id: Optional[str],
                 attrs: dict):
        self._writer = writer
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.span_id = new_span_id()
        self.attrs = attrs
        self._t0 = 0.0

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.time()
        return self

    def __exit__(self, etype, evalue, tb) -> None:
        if etype is not None:
            self.attrs.setdefault("error", f"{etype.__name__}: {evalue}")
        self._writer.emit(self.name, start=self._t0, end=time.time(),
                          trace_id=self.trace_id, span_id=self.span_id,
                          parent_id=self.parent_id, **self.attrs)


class SpanWriter:
    """Appends span records to a JSONL sink. One writer per component per
    process; ``trace_id`` may be bound at construction (workers — one job
    per process) or passed per record (control plane — many jobs)."""

    def __init__(self, path: str, component: str,
                 trace_id: Optional[str] = None,
                 max_bytes: Optional[int] = None):
        self.path = path
        self.component = component
        self.trace_id = trace_id
        if max_bytes is None:
            try:
                max_bytes = int(os.environ.get(SPAN_MAX_BYTES_ENV) or 0)
            except ValueError:
                max_bytes = 0
        self.max_bytes = max(0, int(max_bytes))
        self._lock = threading.Lock()
        # the per-path rotation lock is resolved ONCE here: resolving it
        # per-emit would take the blocking _rotate_locks_guard on the
        # hot path — and inside the SIGTERM handler's dump, where
        # re-acquiring a guard the interrupted main thread holds would
        # deadlock the very teardown being evidenced
        self._rotate = _rotate_lock(path) if self.max_bytes else None
        self._fh = None
        self._warned = False

    @classmethod
    def from_env(cls, component: str,
                 env: Optional[dict] = None) -> Optional["SpanWriter"]:
        """A writer for the operator-rendered span contract, or None when
        this process has no sink configured (spans off — zero cost)."""
        env = os.environ if env is None else env
        path = env.get(SPAN_PATH_ENV)
        if not path:
            return None
        return cls(path, component, trace_id=env.get(TRACE_ID_ENV))

    # ------------------------------------------------------------- emission

    def emit(self, name: str, *, start: float, end: Optional[float] = None,
             trace_id: Optional[str] = None, span_id: Optional[str] = None,
             parent_id: Optional[str] = None, **attrs) -> dict:
        record = {
            "trace_id": trace_id or self.trace_id or "",
            "span_id": span_id or new_span_id(),
            "parent_id": parent_id or "",
            "name": name,
            "component": self.component,
            "start": round(start, 6),
            "end": round(end if end is not None else start, 6),
        }
        if attrs:
            record["attrs"] = attrs
        line = json.dumps(record) + "\n"
        # observability must never kill the work it observes: an
        # unwritable sink (full volume, revoked mount) drops the record
        # — warned once — and the closed handle means the next emit
        # retries the open, so spans resume when the sink recovers
        with self._lock:
            try:
                if self._fh is None:
                    d = os.path.dirname(self.path)
                    if d:
                        os.makedirs(d, exist_ok=True)
                    self._fh = open(self.path, "a")
                if self.max_bytes:
                    self._rotate_if_needed(len(line))
                self._fh.write(line)
                self._fh.flush()
            except OSError as e:
                if not self._warned:
                    self._warned = True
                    import logging
                    logging.getLogger(__name__).warning(
                        "span sink %s unwritable (%s); dropping spans "
                        "until it recovers", self.path, e)
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
        return record

    def _rotate_if_needed(self, incoming: int) -> None:
        """Size-cap rotation (KFTPU_SPAN_MAX_BYTES), safe for the
        deployed shape of MANY writers appending to one sink (operator,
        scheduler, every worker). Two hazards the naive rotate has:

        - a writer holding a handle onto a file ANOTHER writer already
          renamed keeps appending to the stale inode — its spans
          (including flight-record dumps) silently land in ``.1`` and
          vanish from the live trace. Every capped write re-checks the
          handle's inode against the path and reopens on mismatch.
        - a writer rotating off its own stale size clobbers a sibling's
          FRESH active file over the prior generation. Rotation runs
          under a process-wide per-path lock and re-checks the LIVE
          file size first, so only a genuinely over-cap active file is
          ever renamed.

        Cross-process rotation remains best-effort (no file locking in
        scope): the inode re-check bounds the damage to one writer
        reopening a line late, never to silent span loss."""
        try:
            if os.stat(self.path).st_ino != os.fstat(
                    self._fh.fileno()).st_ino:
                self._fh.close()
                self._fh = open(self.path, "a")
        except OSError:
            # path gone mid-check (sibling rotated + nothing rewrote
            # it yet): reopen creates the fresh active generation
            self._fh.close()
            self._fh = open(self.path, "a")
        if self._fh.tell() + incoming <= self.max_bytes or \
                self._fh.tell() == 0:
            return
        # NON-BLOCKING: the SIGTERM flight-record dump writes through a
        # dedicated writer that shares only THIS lock with the main
        # thread — a handler blocking on a lock its interrupted holder
        # can never release would deadlock the teardown. A contended
        # rotation is simply skipped: the write overshoots the cap by
        # one record and the next uncontended write rotates.
        lock = self._rotate
        if not lock.acquire(blocking=False):
            return
        try:
            try:
                live = os.path.getsize(self.path)
            except OSError:
                live = 0
            if live + incoming > self.max_bytes and live > 0:
                self._fh.close()
                self._fh = None
                os.replace(self.path, self.path + ".1")
                self._fh = open(self.path, "a")
            elif os.stat(self.path).st_ino != os.fstat(
                    self._fh.fileno()).st_ino:
                # a sibling rotated while we raced for the lock
                self._fh.close()
                self._fh = open(self.path, "a")
        finally:
            lock.release()

    def event(self, name: str, trace_id: Optional[str] = None,
              **attrs) -> dict:
        """A point event (zero-duration span): phase transitions."""
        return self.emit(name, start=time.time(), trace_id=trace_id, **attrs)

    def span(self, name: str, trace_id: Optional[str] = None,
             parent_id: Optional[str] = None, **attrs) -> _SpanCtx:
        """``with writer.span("restore"): ...`` — timed child span."""
        return _SpanCtx(self, name, trace_id or self.trace_id, parent_id,
                        dict(attrs))

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# One cached writer per component so the control-plane reconcilers can
# instrument without threading a writer through every constructor. The
# cache is bounded by the component count: when the env sink changes
# (tests/bench pointing successive runs at fresh tmp sinks), the stale
# writer is CLOSED and replaced — never accumulated as a leaked fd.
_writers: dict = {}   # component -> (path, SpanWriter)
_writers_lock = threading.Lock()


def default_tracer(component: str) -> Optional[SpanWriter]:
    path = os.environ.get(SPAN_PATH_ENV)
    if not path:
        return None
    with _writers_lock:
        cached = _writers.get(component)
        if cached is not None:
            old_path, w = cached
            if old_path == path:
                return w
            w.close()
        w = SpanWriter(path, component)
        _writers[component] = (path, w)
        return w


def reset_default_tracers() -> None:
    """Close and drop every cached control-plane writer — the trace
    analog of registry.reset_default_registry()."""
    with _writers_lock:
        for _, w in _writers.values():
            w.close()
        _writers.clear()


@contextlib.contextmanager
def adopt_trace_env(env_map: dict):
    """Temporarily adopt the operator-rendered trace contract
    (KFTPU_TRACE_ID / KFTPU_SPAN_PATH) from a pod's env map — the
    in-process soak segments' stand-in for actually running inside the
    pod, so their worker spans stitch onto the job's control-plane
    trace. Shared by every soak (scheduler/soak.py, cluster/chaos.py)
    so the adoption logic cannot drift."""
    saved: dict = {}
    for key in (TRACE_ID_ENV, SPAN_PATH_ENV):
        value = env_map.get(key)
        if value:
            saved[key] = os.environ.get(key)
            os.environ[key] = value
    try:
        yield
    finally:
        for key, old in saved.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


# -------------------------------------------------------------- reading back

def load_spans(path: str, trace_id: Optional[str] = None) -> list[dict]:
    """All span records in the sink (optionally one trace's), sorted by
    (start, end) so the list reads as the timeline. Torn/garbage lines
    are skipped — a reader must cope with a writer mid-append."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) or "name" not in rec:
                    continue
                if trace_id is None or rec.get("trace_id") == trace_id:
                    out.append(rec)
    except OSError:
        return []
    out.sort(key=lambda r: (r.get("start", 0.0), r.get("end", 0.0)))
    return out


def reconstruct(path: str, trace_id: str) -> dict:
    """One job's end-to-end timeline from the JSONL alone:
    ``{"traceId", "events": [ordered spans], "names": [...],
    "wallSeconds"}``. ``names`` is the phase fingerprint tests assert
    against (queued → bound → created → running → window... → done)."""
    spans = load_spans(path, trace_id=trace_id)
    events = [{
        "name": s["name"],
        "component": s.get("component", ""),
        "start": s.get("start", 0.0),
        "end": s.get("end", s.get("start", 0.0)),
        "durationSeconds": round(
            max(0.0, s.get("end", 0.0) - s.get("start", 0.0)), 6),
        "attrs": s.get("attrs", {}),
    } for s in spans]
    # max(end) - min(start), not last-by-start's end: an early-started
    # long span (the whole-run profile capture) may outlive every later
    # point event
    wall = (max(s.get("end", 0.0) for s in spans)
            - min(s.get("start", 0.0) for s in spans)) if spans else 0.0
    return {"traceId": trace_id, "events": events,
            "names": [e["name"] for e in events],
            "wallSeconds": round(max(0.0, wall), 6)}
