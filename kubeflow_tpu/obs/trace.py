"""Cross-layer trace spans: one trace id per TPUJob, JSONL span records.

The scaling wins Podracer (arXiv:2104.06272) and TF-Replicator
(arXiv:1902.00465) attribute to per-stage accounting need the stages
STITCHED: a job's queue wait, its pod start, its first step, and every
training window must reconstruct as ONE timeline. The contract:

- A ``trace_id`` is minted the first time the control plane touches a
  TPUJob (scheduler pass or operator reconcile — whichever sees it
  first) and persisted as the ``observability.kubeflow.org/trace-id``
  annotation, so every later actor agrees on it.
- The operator renders it into every worker pod as ``KFTPU_TRACE_ID``
  (next to the pod-identity env), and forwards its own
  ``KFTPU_SPAN_PATH`` so workers write spans where the operator does.
- Every component appends span records to that JSONL sink:
  ``{"trace_id", "span_id", "parent_id", "name", "component",
  "start", "end", "attrs"}`` — wall-clock seconds, so spans from
  different processes order on one axis. Point events (queued, bound,
  running) are zero-duration spans.
- ``reconstruct()`` reads the sink back into the end-to-end timeline:
  queued → bound → pod-start → running → windows → done. The dashboard
  serves it at ``/api/obs/jobs/<ns>/<name>``; tests and ``bench.py
  --mode obs`` assert on it.

Writers are append-only and line-atomic (one ``write()`` per record), so
scheduler, operator, and in-process workers can share a sink file the
way the chaos/scheduler soaks share a FakeCluster. jax-free, stdlib
only; the jax.profiler capture (``runtime/metrics.py profile_trace``)
hooks in as a child span around its start/stop.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Optional

# env contract (rendered by controllers/tpujob.py into worker pods;
# tests/test_lint.py pins the plumbing)
TRACE_ID_ENV = "KFTPU_TRACE_ID"
SPAN_PATH_ENV = "KFTPU_SPAN_PATH"

# where the minted trace id persists on the job object (the one value
# every component — scheduler, operator, worker, dashboard — agrees on)
TRACE_ID_ANNOTATION = "observability.kubeflow.org/trace-id"


def mint_trace_id(uid: str = "") -> str:
    """A fresh trace id — DERIVED from the object's uid when one exists,
    so concurrent minters (the scheduler pass and the operator both
    waking on the same ADDED event) compute the SAME id and neither
    side's early spans are orphaned by a lost patch race."""
    if uid:
        import hashlib
        return hashlib.sha1(uid.encode()).hexdigest()[:16]
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class _SpanCtx:
    """Context manager for a timed span; emits on exit (errors included —
    a failed phase's duration is still its duration)."""

    def __init__(self, writer: "SpanWriter", name: str,
                 trace_id: Optional[str], parent_id: Optional[str],
                 attrs: dict):
        self._writer = writer
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.span_id = new_span_id()
        self.attrs = attrs
        self._t0 = 0.0

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.time()
        return self

    def __exit__(self, etype, evalue, tb) -> None:
        if etype is not None:
            self.attrs.setdefault("error", f"{etype.__name__}: {evalue}")
        self._writer.emit(self.name, start=self._t0, end=time.time(),
                          trace_id=self.trace_id, span_id=self.span_id,
                          parent_id=self.parent_id, **self.attrs)


class SpanWriter:
    """Appends span records to a JSONL sink. One writer per component per
    process; ``trace_id`` may be bound at construction (workers — one job
    per process) or passed per record (control plane — many jobs)."""

    def __init__(self, path: str, component: str,
                 trace_id: Optional[str] = None):
        self.path = path
        self.component = component
        self.trace_id = trace_id
        self._lock = threading.Lock()
        self._fh = None
        self._warned = False

    @classmethod
    def from_env(cls, component: str,
                 env: Optional[dict] = None) -> Optional["SpanWriter"]:
        """A writer for the operator-rendered span contract, or None when
        this process has no sink configured (spans off — zero cost)."""
        env = os.environ if env is None else env
        path = env.get(SPAN_PATH_ENV)
        if not path:
            return None
        return cls(path, component, trace_id=env.get(TRACE_ID_ENV))

    # ------------------------------------------------------------- emission

    def emit(self, name: str, *, start: float, end: Optional[float] = None,
             trace_id: Optional[str] = None, span_id: Optional[str] = None,
             parent_id: Optional[str] = None, **attrs) -> dict:
        record = {
            "trace_id": trace_id or self.trace_id or "",
            "span_id": span_id or new_span_id(),
            "parent_id": parent_id or "",
            "name": name,
            "component": self.component,
            "start": round(start, 6),
            "end": round(end if end is not None else start, 6),
        }
        if attrs:
            record["attrs"] = attrs
        line = json.dumps(record) + "\n"
        # observability must never kill the work it observes: an
        # unwritable sink (full volume, revoked mount) drops the record
        # — warned once — and the closed handle means the next emit
        # retries the open, so spans resume when the sink recovers
        with self._lock:
            try:
                if self._fh is None:
                    d = os.path.dirname(self.path)
                    if d:
                        os.makedirs(d, exist_ok=True)
                    self._fh = open(self.path, "a")
                self._fh.write(line)
                self._fh.flush()
            except OSError as e:
                if not self._warned:
                    self._warned = True
                    import logging
                    logging.getLogger(__name__).warning(
                        "span sink %s unwritable (%s); dropping spans "
                        "until it recovers", self.path, e)
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
        return record

    def event(self, name: str, trace_id: Optional[str] = None,
              **attrs) -> dict:
        """A point event (zero-duration span): phase transitions."""
        return self.emit(name, start=time.time(), trace_id=trace_id, **attrs)

    def span(self, name: str, trace_id: Optional[str] = None,
             parent_id: Optional[str] = None, **attrs) -> _SpanCtx:
        """``with writer.span("restore"): ...`` — timed child span."""
        return _SpanCtx(self, name, trace_id or self.trace_id, parent_id,
                        dict(attrs))

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# One cached writer per component so the control-plane reconcilers can
# instrument without threading a writer through every constructor. The
# cache is bounded by the component count: when the env sink changes
# (tests/bench pointing successive runs at fresh tmp sinks), the stale
# writer is CLOSED and replaced — never accumulated as a leaked fd.
_writers: dict = {}   # component -> (path, SpanWriter)
_writers_lock = threading.Lock()


def default_tracer(component: str) -> Optional[SpanWriter]:
    path = os.environ.get(SPAN_PATH_ENV)
    if not path:
        return None
    with _writers_lock:
        cached = _writers.get(component)
        if cached is not None:
            old_path, w = cached
            if old_path == path:
                return w
            w.close()
        w = SpanWriter(path, component)
        _writers[component] = (path, w)
        return w


def reset_default_tracers() -> None:
    """Close and drop every cached control-plane writer — the trace
    analog of registry.reset_default_registry()."""
    with _writers_lock:
        for _, w in _writers.values():
            w.close()
        _writers.clear()


# -------------------------------------------------------------- reading back

def load_spans(path: str, trace_id: Optional[str] = None) -> list[dict]:
    """All span records in the sink (optionally one trace's), sorted by
    (start, end) so the list reads as the timeline. Torn/garbage lines
    are skipped — a reader must cope with a writer mid-append."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) or "name" not in rec:
                    continue
                if trace_id is None or rec.get("trace_id") == trace_id:
                    out.append(rec)
    except OSError:
        return []
    out.sort(key=lambda r: (r.get("start", 0.0), r.get("end", 0.0)))
    return out


def reconstruct(path: str, trace_id: str) -> dict:
    """One job's end-to-end timeline from the JSONL alone:
    ``{"traceId", "events": [ordered spans], "names": [...],
    "wallSeconds"}``. ``names`` is the phase fingerprint tests assert
    against (queued → bound → created → running → window... → done)."""
    spans = load_spans(path, trace_id=trace_id)
    events = [{
        "name": s["name"],
        "component": s.get("component", ""),
        "start": s.get("start", 0.0),
        "end": s.get("end", s.get("start", 0.0)),
        "durationSeconds": round(
            max(0.0, s.get("end", 0.0) - s.get("start", 0.0)), 6),
        "attrs": s.get("attrs", {}),
    } for s in spans]
    # max(end) - min(start), not last-by-start's end: an early-started
    # long span (the whole-run profile capture) may outlive every later
    # point event
    wall = (max(s.get("end", 0.0) for s in spans)
            - min(s.get("start", 0.0) for s in spans)) if spans else 0.0
    return {"traceId": trace_id, "events": events,
            "names": [e["name"] for e in events],
            "wallSeconds": round(max(0.0, wall), 6)}
