"""Katib-equivalent hyperparameter search subsystem.

The reference deploys katib (vizier core + MySQL + per-algorithm suggestion
services + studyjob-controller, kubeflow/katib/*.libsonnet). Here the same
capability is native: suggestion algorithms are in-process engines
(suggestion.py), the observation store is VizierDB with an optional HTTP
front (vizier.py), and the StudyJob controller drives TPUJob trials through
the same controller runtime as the training operator (studyjob.py).
"""

from .suggestion import (ParameterConfig, Suggestion, make_suggestion,
                         SUGGESTION_ALGORITHMS)
from .vizier import VizierDB, VizierService
from .studyjob import StudyJobReconciler

__all__ = [
    "ParameterConfig", "Suggestion", "make_suggestion",
    "SUGGESTION_ALGORITHMS", "VizierDB", "VizierService",
    "StudyJobReconciler",
]
