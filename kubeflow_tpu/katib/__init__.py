"""Katib-equivalent hyperparameter search subsystem.

The reference deploys katib (vizier core + MySQL + per-algorithm suggestion
services + studyjob-controller, kubeflow/katib/*.libsonnet). Here the same
capability is native: suggestion algorithms are in-process engines
(suggestion.py), the observation store is VizierDB with an optional HTTP
front (vizier.py), and the search object is the Experiment CRD
(api/experiment.py) reconciled by controllers/experiment.py — the legacy
StudyJob shape survives only as a compat converter (studyjob.py).
"""

from .suggestion import (ParameterConfig, Suggestion, make_suggestion,
                         SUGGESTION_ALGORITHMS)
from .vizier import VizierDB, VizierService
from .studyjob import StudyJobCompatReconciler, studyjob_to_experiment

__all__ = [
    "ParameterConfig", "Suggestion", "make_suggestion",
    "SUGGESTION_ALGORITHMS", "VizierDB", "VizierService",
    "StudyJobCompatReconciler", "studyjob_to_experiment",
]
