"""Vizier-core analog: study/trial registry + observation metric store.

The reference deploys vizier-core + MySQL + a REST front
(kubeflow/katib/vizier.libsonnet:4-20) and scrapes worker metrics into it
via per-trial metrics-collector CronJobs
(studyjobcontroller.libsonnet:131-147). Here the store is an in-process DB
(thread-safe, snapshot-serializable) with an optional stdlib HTTP front;
workers report observations either directly (in-process), over HTTP
(``report_observation`` with the KFTPU_VIZIER_URL env contract), or by
writing a ``<trial>-metrics`` ConfigMap that the StudyJob controller
collects (the metrics-collector path, no sidecar needed).
"""

from __future__ import annotations

import json
import os
import threading
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

VIZIER_URL_ENV = "KFTPU_VIZIER_URL"
STUDY_ENV = "KFTPU_STUDY"
TRIAL_ENV = "KFTPU_TRIAL"


@dataclass
class Observation:
    trial: str
    metric: str
    value: float
    step: int = 0


@dataclass
class TrialRecord:
    name: str
    parameters: dict[str, Any] = field(default_factory=dict)
    status: str = "Pending"   # Pending | Running | Succeeded | Failed
    objective: Optional[float] = None


@dataclass
class StudyRecord:
    name: str
    objective_name: str = "loss"
    optimization_type: str = "minimize"
    metrics_names: list[str] = field(default_factory=list)
    trials: dict[str, TrialRecord] = field(default_factory=dict)
    observations: list[Observation] = field(default_factory=list)


class VizierDB:
    def __init__(self):
        self._studies: dict[str, StudyRecord] = {}
        self._lock = threading.RLock()

    def create_study(self, name: str, objective_name: str = "loss",
                     optimization_type: str = "minimize",
                     metrics_names: Optional[list[str]] = None) -> StudyRecord:
        with self._lock:
            if name not in self._studies:
                self._studies[name] = StudyRecord(
                    name=name, objective_name=objective_name,
                    optimization_type=optimization_type,
                    metrics_names=list(metrics_names or []))
            return self._studies[name]

    def get_study(self, name: str) -> Optional[StudyRecord]:
        with self._lock:
            return self._studies.get(name)

    def list_studies(self) -> list[str]:
        with self._lock:
            return sorted(self._studies)

    def register_trial(self, study: str, trial: str,
                       parameters: dict[str, Any]) -> None:
        with self._lock:
            s = self.create_study(study)
            s.trials.setdefault(trial, TrialRecord(name=trial,
                                                   parameters=parameters))

    def set_trial_status(self, study: str, trial: str, status: str) -> None:
        with self._lock:
            s = self.create_study(study)
            s.trials.setdefault(trial, TrialRecord(name=trial)).status = status

    def report(self, study: str, trial: str, metric: str, value: float,
               step: int = 0) -> None:
        with self._lock:
            s = self.create_study(study)
            s.observations.append(Observation(trial, metric, float(value), step))

    def objective_of(self, study: str, trial: str) -> Optional[float]:
        """Latest reported value of the study's objective metric."""
        with self._lock:
            s = self._studies.get(study)
            if s is None:
                return None
            latest: Optional[Observation] = None
            for o in s.observations:
                if o.trial == trial and o.metric == s.objective_name:
                    if latest is None or o.step >= latest.step:
                        latest = o
            return latest.value if latest else None

    def trial_metrics(self, study: str, trial: str) -> dict[str, float]:
        with self._lock:
            s = self._studies.get(study)
            out: dict[str, float] = {}
            if s is None:
                return out
            for o in sorted(s.observations, key=lambda o: o.step):
                if o.trial == trial:
                    out[o.metric] = o.value
            return out

    def best_trial(self, study: str) -> Optional[TrialRecord]:
        with self._lock:
            s = self._studies.get(study)
            if s is None:
                return None
            sign = -1.0 if s.optimization_type == "minimize" else 1.0
            done = [t for t in s.trials.values()
                    if t.objective is not None and t.status == "Succeeded"]
            if not done:
                return None
            return max(done, key=lambda t: sign * t.objective)

    def to_snapshot(self) -> dict:
        with self._lock:
            return {
                name: {
                    "objective_name": s.objective_name,
                    "optimization_type": s.optimization_type,
                    "metrics_names": s.metrics_names,
                    "trials": {t.name: {"parameters": t.parameters,
                                        "status": t.status,
                                        "objective": t.objective}
                               for t in s.trials.values()},
                    "observations": [[o.trial, o.metric, o.value, o.step]
                                     for o in s.observations],
                }
                for name, s in self._studies.items()
            }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "VizierDB":
        db = cls()
        for name, sd in (snap or {}).items():
            s = db.create_study(name, sd.get("objective_name", "loss"),
                                sd.get("optimization_type", "minimize"),
                                sd.get("metrics_names"))
            for tname, td in sd.get("trials", {}).items():
                rec = TrialRecord(name=tname,
                                  parameters=td.get("parameters", {}),
                                  status=td.get("status", "Pending"),
                                  objective=td.get("objective"))
                s.trials[tname] = rec
            for trial, metric, value, step in sd.get("observations", []):
                s.observations.append(Observation(trial, metric, value, step))
        return db


class VizierService:
    """HTTP front over VizierDB (the vizier REST + UI API analog).

    Routes:
      POST /api/v1/observation           {study, trial, metric, value, step}
      GET  /api/v1/studies
      GET  /api/v1/studies/<name>        study + trials + best
      GET  /healthz
    """

    def __init__(self, db: Optional[VizierDB] = None, host: str = "127.0.0.1",
                 port: int = 0):
        self.db = db or VizierDB()
        self._httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="vizier-http")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def _make_handler(svc: VizierService):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, payload) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                return self._send(200, {"ok": True})
            if self.path == "/api/v1/studies":
                return self._send(200, {"studies": svc.db.list_studies()})
            if self.path.startswith("/api/v1/studies/"):
                name = self.path.rsplit("/", 1)[1]
                study = svc.db.get_study(name)
                if study is None:
                    return self._send(404, {"error": f"study {name} not found"})
                best = svc.db.best_trial(name)
                return self._send(200, {
                    "name": study.name,
                    "objectiveName": study.objective_name,
                    "optimizationType": study.optimization_type,
                    "trials": [
                        {"name": t.name, "parameters": t.parameters,
                         "status": t.status, "objective": t.objective}
                        for t in study.trials.values()
                    ],
                    "bestTrial": best.name if best else None,
                })
            return self._send(404, {"error": "not found"})

        def do_POST(self):
            if self.path != "/api/v1/observation":
                return self._send(404, {"error": "not found"})
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
                svc.db.report(req["study"], req["trial"], req["metric"],
                              float(req["value"]), int(req.get("step", 0)))
            except (KeyError, ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": str(e)})
            return self._send(200, {"ok": True})

    return Handler


def report_observation(metric: str, value: float, step: int = 0,
                       url: Optional[str] = None, study: Optional[str] = None,
                       trial: Optional[str] = None) -> bool:
    """Worker-side reporter. Reads the KFTPU_VIZIER_URL / KFTPU_STUDY /
    KFTPU_TRIAL env contract the StudyJob controller injects (the TF_CONFIG
    idiom applied to metrics collection); no-op when not under a study."""
    url = url or os.environ.get(VIZIER_URL_ENV)
    study = study or os.environ.get(STUDY_ENV)
    trial = trial or os.environ.get(TRIAL_ENV)
    if not (url and study and trial):
        return False
    payload = json.dumps({"study": study, "trial": trial, "metric": metric,
                          "value": value, "step": step}).encode()
    req = urllib.request.Request(
        url.rstrip("/") + "/api/v1/observation", data=payload,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=5) as resp:
        return resp.status == 200
