"""StudyJob v1alpha1 compat: convert to the Experiment API.

The StudyJob shape (kind StudyJob, kubeflow.org/v1alpha1 — field names
from kubeflow/examples/prototypes/katib-studyjob-test-v1alpha1.jsonnet)
was the reference's HP-search object; this platform's native object is
``Experiment`` (api/experiment.py), reconciled by
controllers/experiment.py. Two competing search APIs must never coexist,
so this module is now a THIN compat layer:

- ``studyjob_to_experiment(manifest)`` — pure conversion of a StudyJob
  manifest into an Experiment manifest (the admission-time migration
  path; also what ``kftpu`` tooling uses to upgrade stored YAML).
- ``StudyJobCompatReconciler`` — watches legacy StudyJob objects,
  creates the converted Experiment (owner-ref'd for cascade delete),
  and mirrors the Experiment's rollup + terminal conditions back onto
  the StudyJob status so old clients keep seeing progress.

The trial loop itself (suggest → spawn → collect → early-stop → roll up)
lives ONLY in controllers/experiment.py.

StudyJob spec, for reference:

  studyName, owner, optimizationtype: maximize|minimize,
  objectivevaluename, metricsnames: [..],
  parameterconfigs: [{name, parametertype, feasible: {min, max, list}}],
  suggestionSpec: {suggestionAlgorithm, requestNumber,
                   suggestionParameters: [{name, value}]},
  workerSpec: {template: <job manifest>, injectParameters: true},
  maxTrials, maxFailedTrials
"""

from __future__ import annotations

import logging

from ..api import k8s
from ..api.experiment import (DEFAULT_OBJECTIVE_METRIC,
                              EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                              OBSERVATION_ANNOTATION, TRIAL_LABEL)
from ..api.trainingjob import (COND_FAILED, COND_RUNNING, COND_SUCCEEDED,
                               KF_API_VERSION_V1ALPHA1)
from ..cluster.client import KubeClient, NotFoundError
from ..controllers.runtime import Key, Reconciler, Result, status_snapshot

log = logging.getLogger(__name__)

STUDYJOB_API_VERSION = KF_API_VERSION_V1ALPHA1
STUDYJOB_KIND = "StudyJob"
STUDY_LABEL = "katib.kubeflow.org/study"

__all__ = ["STUDYJOB_API_VERSION", "STUDYJOB_KIND", "STUDY_LABEL",
           "TRIAL_LABEL", "OBSERVATION_ANNOTATION",
           "studyjob_to_experiment", "StudyJobCompatReconciler"]

# StudyJob algorithms with no Experiment equivalent degrade to random
# search (grid survives; hyperband/bayesianoptimization were in-process
# conveniences the Experiment API deliberately does not carry).
_ALGORITHM_MAP = {"grid": "grid", "random": "random"}


def studyjob_to_experiment(manifest: dict) -> dict:
    """Convert a StudyJob v1alpha1 manifest into an Experiment manifest.

    Pure function of the input; raises ValueError on shapes that cannot
    be expressed (missing workerSpec.template, empty parameterconfigs).
    The result still goes through ``Experiment.from_manifest`` admission
    when applied — this only maps field names.
    """
    if manifest.get("kind", STUDYJOB_KIND) != STUDYJOB_KIND:
        raise ValueError(
            f"kind {manifest.get('kind')!r} is not {STUDYJOB_KIND}")
    meta = manifest.get("metadata", {}) or {}
    spec = manifest.get("spec", {}) or {}

    worker = spec.get("workerSpec", {}) or {}
    template = worker.get("template")
    if not template:
        raise ValueError("workerSpec.template is required")

    parameters = []
    for pc in spec.get("parameterconfigs", []) or []:
        feasible = pc.get("feasible", {}) or {}
        p = {"name": pc.get("name"),
             "type": pc.get("parametertype", "double")}
        if feasible.get("min") is not None:
            p["min"] = float(feasible["min"])
        if feasible.get("max") is not None:
            p["max"] = float(feasible["max"])
        if feasible.get("list") is not None:
            p["values"] = list(feasible["list"])
        parameters.append(p)
    if not parameters:
        raise ValueError("parameterconfigs must name at least one "
                         "search dimension")

    sugg = spec.get("suggestionSpec", {}) or {}
    algorithm = _ALGORITHM_MAP.get(
        str(sugg.get("suggestionAlgorithm", "random")).lower(), "random")
    settings = {p["name"]: p["value"]
                for p in sugg.get("suggestionParameters", []) or []}
    request = int(sugg.get("requestNumber", 3))

    # StudyJob without maxTrials ran 4 rounds of requestNumber for
    # open-ended samplers; grid enumerated itself. Experiment requires a
    # finite budget, so grid gets a generous cap (its engine exhausts
    # first) and the rest keep the 4-round default.
    if spec.get("maxTrials") is not None:
        max_trials = int(spec["maxTrials"])
    elif algorithm == "grid":
        max_trials = 1 << 10
    else:
        max_trials = 4 * request

    exp_spec = {
        "objective": {
            "type": spec.get("optimizationtype", "minimize"),
            "metric": spec.get("objectivevaluename",
                               DEFAULT_OBJECTIVE_METRIC),
        },
        "algorithm": ({"name": algorithm, "settings": settings}
                      if settings else {"name": algorithm}),
        "parameters": parameters,
        "maxTrials": max_trials,
        "parallelism": max(1, request),
        "trialTemplate": template,
    }
    if spec.get("maxFailedTrials") is not None:
        exp_spec["maxFailedTrials"] = int(spec["maxFailedTrials"])
    if not worker.get("injectParameters", True):
        exp_spec["injectParameters"] = False

    out_meta = {"name": meta.get("name", ""),
                "namespace": meta.get("namespace", "default")}
    labels = dict(meta.get("labels", {}) or {})
    labels[STUDY_LABEL] = spec.get("studyName") or meta.get("name", "")
    out_meta["labels"] = labels
    return {"apiVersion": EXPERIMENT_API_VERSION, "kind": EXPERIMENT_KIND,
            "metadata": out_meta, "spec": exp_spec}


#: status fields mirrored from the Experiment back onto the StudyJob
_MIRROR_FIELDS = ("trials", "trialsTotal", "trialsRunning",
                  "trialsSucceeded", "trialsFailed", "trialsStopped",
                  "bestTrial", "trialsPerHour", "chipHours",
                  "warmStartFraction")


class StudyJobCompatReconciler(Reconciler):
    """Legacy adapter: StudyJob → owned Experiment, status mirrored back.

    Deliberately does NOT run trials. The owned Experiment is the single
    source of truth; deleting the StudyJob cascades to the Experiment
    (and through it to the trial jobs).
    """

    primary = (STUDYJOB_API_VERSION, STUDYJOB_KIND)
    owns = [(EXPERIMENT_API_VERSION, EXPERIMENT_KIND)]

    def __init__(self, **_legacy):
        # vizier=/vizier_url=/seed= accepted for drop-in compatibility
        # with the retired StudyJobReconciler signature, ignored: metric
        # collection now rides the Experiment contract.
        if _legacy:
            log.debug("StudyJobCompatReconciler ignoring legacy "
                      "arguments: %s", sorted(_legacy))

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            manifest = client.get(STUDYJOB_API_VERSION, STUDYJOB_KIND,
                                  ns, name)
        except NotFoundError:
            return Result()  # owner ref cascades the Experiment away

        if k8s.condition_true(manifest, COND_SUCCEEDED) or \
                k8s.condition_true(manifest, COND_FAILED):
            return Result()

        exp = client.get_or_none(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                                 ns, name)
        if exp is None:
            try:
                exp = studyjob_to_experiment(manifest)
            except ValueError as e:
                self._set_condition(client, manifest, COND_FAILED,
                                    "InvalidSpec", str(e))
                return Result()
            k8s.set_owner(exp, manifest)
            client.create(exp)
            log.info("studyjob %s/%s converted to Experiment", ns, name)
            return Result()

        # mirror the experiment's rollup + terminal state
        status = dict(manifest.get("status", {}) or {})
        before = status_snapshot(status)
        exp_status = exp.get("status", {}) or {}
        for f in _MIRROR_FIELDS:
            if f in exp_status:
                status[f] = exp_status[f]
        if status_snapshot(status) != before:
            fresh = client.get(STUDYJOB_API_VERSION, STUDYJOB_KIND, ns,
                               name)
            merged = dict(fresh.get("status", {}))
            merged.update(
                {k: v for k, v in status.items() if k != "conditions"})
            fresh["status"] = merged
            client.update_status(fresh)

        for ctype, reason in ((COND_SUCCEEDED, "StudyCompleted"),
                              (COND_FAILED, "ExperimentFailed")):
            if k8s.condition_true(exp, ctype) and \
                    not k8s.condition_true(manifest, ctype):
                self._set_condition(
                    client, manifest, ctype, reason,
                    f"mirrored from Experiment {ns}/{name}")
                return Result()
        if k8s.condition_true(exp, COND_RUNNING) and \
                not k8s.condition_true(manifest, COND_RUNNING):
            self._set_condition(client, manifest, COND_RUNNING,
                                "TrialsRunning", "trials in progress")
        return Result()

    def _set_condition(self, client: KubeClient, manifest: dict,
                       ctype: str, reason: str, message: str) -> None:
        fresh = client.get(STUDYJOB_API_VERSION, STUDYJOB_KIND,
                           k8s.namespace_of(manifest, "default"),
                           k8s.name_of(manifest))
        k8s.set_condition(fresh, k8s.Condition(ctype, "True", reason,
                                               message))
        client.update_status(fresh)
