"""StudyJob controller: HP-search trials as gang-scheduled training jobs.

The reference's studyjob-controller (deployed by
kubeflow/katib/studyjobcontroller.libsonnet:294-323) runs the loop in
SURVEY.md §3.5: ask a suggestion service for assignments, stamp them into the
workerTemplate, create per-trial worker jobs, inject a metrics-collector, and
iterate until done. Here the worker jobs are our TPUJob/TFJob kinds (so every
trial is a gang-scheduled TPU slice), suggestions are in-process engines, and
metric collection is the VizierDB contract (env-injected reporter URL or a
``<trial>-metrics`` ConfigMap) instead of a log-scraping CronJob.

StudyJob spec (kind StudyJob, kubeflow.org/v1alpha1 — schema registered by
manifests/katib.py, field names from
kubeflow/examples/prototypes/katib-studyjob-test-v1alpha1.jsonnet):

  studyName, owner, optimizationtype: maximize|minimize, objectivevaluename,
  metricsnames: [..], parameterconfigs: [{name, parametertype, feasible}],
  suggestionSpec: {suggestionAlgorithm, requestNumber,
                   suggestionParameters: [{name, value}]},
  workerSpec: {template: <TPUJob/TFJob/PyTorchJob/MPIJob manifest>,
               injectParameters: true},
  maxTrials, maxFailedTrials
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Optional

from ..api import k8s
from ..api.trainingjob import (COND_CREATED, COND_FAILED, COND_RUNNING,
                               COND_SUCCEEDED, JOB_KINDS, KF_API_VERSION_V1ALPHA1,
                               KF_API_VERSION_V1BETA2, TPU_API_VERSION)
from ..cluster.client import KubeClient, NotFoundError
from ..controllers.runtime import (Key, Reconciler, Result,
                                   status_snapshot)
from .suggestion import Suggestion, make_suggestion, parse_parameter_configs
from .vizier import STUDY_ENV, TRIAL_ENV, VIZIER_URL_ENV, VizierDB

log = logging.getLogger(__name__)

STUDYJOB_API_VERSION = KF_API_VERSION_V1ALPHA1
STUDYJOB_KIND = "StudyJob"
TRIAL_LABEL = "katib.kubeflow.org/trial"
STUDY_LABEL = "katib.kubeflow.org/study"
OBSERVATION_ANNOTATION = "kubeflow.org/observation"

_JOB_API = {"TPUJob": TPU_API_VERSION, "TFJob": KF_API_VERSION_V1BETA2,
            "PyTorchJob": KF_API_VERSION_V1BETA2,
            "MPIJob": KF_API_VERSION_V1ALPHA1}

# trial states recorded in StudyJob status
T_PENDING = "Pending"
T_RUNNING = "Running"
T_SUCCEEDED = "Succeeded"
T_FAILED = "Failed"


@dataclass
class _StudyState:
    """In-memory per-study state (suggestion engines are stateful; the
    reference keeps the analog inside vizier-core + the suggestion service
    processes). Rebuilt from status on controller restart."""
    engine: Suggestion
    sign: float
    next_index: int = 0
    # trial name -> exact parameter dict handed to the engine
    params: dict[str, dict[str, Any]] = field(default_factory=dict)
    collect_retries: dict[str, int] = field(default_factory=dict)


def _inject_env(manifest: dict, env: dict[str, str]) -> None:
    """Append env vars to every container list in the manifest (the worker
    template's shape varies by job kind, so walk generically)."""
    def walk(node):
        if isinstance(node, dict):
            containers = node.get("containers")
            if isinstance(containers, list):
                for c in containers:
                    if isinstance(c, dict):
                        ce = c.setdefault("env", [])
                        present = {e.get("name") for e in ce}
                        for name, value in env.items():
                            if name not in present:
                                ce.append({"name": name, "value": value})
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)
    walk(manifest)


def _inject_args(manifest: dict, assignments: dict[str, Any]) -> None:
    """Append ``--name=value`` pairs to the first container's args — the
    katib workerTemplate idiom (parameter names are literal CLI flags,
    katib-studyjob-test-v1alpha1.jsonnet parameterconfigs)."""
    def first_containers(node):
        if isinstance(node, dict):
            containers = node.get("containers")
            if isinstance(containers, list) and containers:
                return containers
            for v in node.values():
                found = first_containers(v)
                if found:
                    return found
        elif isinstance(node, list):
            for v in node:
                found = first_containers(v)
                if found:
                    return found
        return None

    containers = first_containers(manifest) or []
    for c in containers:
        args = c.setdefault("args", [])
        for name, value in assignments.items():
            flag = name if name.startswith("-") else f"--{name}"
            args.append(f"{flag}={value}")


class StudyJobReconciler(Reconciler):
    primary = (STUDYJOB_API_VERSION, STUDYJOB_KIND)
    owns = [(TPU_API_VERSION, "TPUJob"), (KF_API_VERSION_V1BETA2, "TFJob"),
            (KF_API_VERSION_V1BETA2, "PyTorchJob"),
            (KF_API_VERSION_V1ALPHA1, "MPIJob")]

    #: how many reconciles to wait for a finished trial's metrics before
    #: declaring them unavailable (the metrics-collector retry budget)
    max_collect_retries = 5

    def __init__(self, vizier: Optional[VizierDB] = None,
                 vizier_url: Optional[str] = None, seed: int = 0):
        self.vizier = vizier or VizierDB()
        self.vizier_url = vizier_url
        self.seed = seed
        self._states: dict[str, _StudyState] = {}

    # -- state ---------------------------------------------------------------

    def _study_id(self, manifest: dict) -> str:
        return manifest.get("metadata", {}).get("uid") or k8s.name_of(manifest)

    def _engine_state(self, manifest: dict) -> _StudyState:
        sid = self._study_id(manifest)
        if sid in self._states:
            return self._states[sid]
        spec = manifest.get("spec", {})
        sugg = spec.get("suggestionSpec", {}) or {}
        settings = {p["name"]: p["value"]
                    for p in sugg.get("suggestionParameters", []) or []}
        params = parse_parameter_configs(spec.get("parameterconfigs", []))
        engine = make_suggestion(sugg.get("suggestionAlgorithm", "random"),
                                 params, seed=self.seed, settings=settings)
        sign = -1.0 if spec.get("optimizationtype", "minimize") == "minimize" \
            else 1.0
        state = _StudyState(engine=engine, sign=sign)
        # restart recovery: replay finished trials from status so the engine
        # (and grid cursor) catch up to where the previous process stopped
        trials = manifest.get("status", {}).get("trials", []) or []
        if trials:
            state.next_index = len(trials)
            replayed = engine.suggest(len(trials))  # advance grid/hyperband
            del replayed
            for t in trials:
                state.params[t["name"]] = t.get("parameters", {})
                if t.get("status") == T_SUCCEEDED and t.get("objective") is not None:
                    engine.observe(t.get("parameters", {}),
                                   state.sign * float(t["objective"]))
                elif t.get("status") == T_FAILED:
                    # failed trials must settle too, or hyperband's pending
                    # queue re-suggests known-failed configs after restart
                    engine.observe_failure(t.get("parameters", {}))
        self._states[sid] = state
        return state

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            manifest = client.get(STUDYJOB_API_VERSION, STUDYJOB_KIND, ns, name)
        except NotFoundError:
            return Result()  # cascade deletion reaps trials via owner refs

        status = manifest.setdefault("status", {})
        if k8s.condition_true(manifest, COND_SUCCEEDED) or \
                k8s.condition_true(manifest, COND_FAILED):
            return Result()
        status_before = status_snapshot(status)

        spec = manifest.get("spec", {})
        study = spec.get("studyName") or name
        objective = spec.get("objectivevaluename", "loss")
        self.vizier.create_study(
            study, objective_name=objective,
            optimization_type=spec.get("optimizationtype", "minimize"),
            metrics_names=spec.get("metricsnames"))

        worker = spec.get("workerSpec", {}) or {}
        template = worker.get("template")
        if not template:
            self._finish(client, manifest, COND_FAILED,
                         "InvalidSpec", "workerSpec.template is required")
            return Result()
        kind = template.get("kind", "TPUJob")
        if kind not in JOB_KINDS:
            self._finish(client, manifest, COND_FAILED, "InvalidSpec",
                         f"workerSpec.template kind {kind!r} not one of "
                         f"{JOB_KINDS}")
            return Result()

        try:
            state = self._engine_state(manifest)
        except ValueError as e:
            self._finish(client, manifest, COND_FAILED, "InvalidSpec", str(e))
            return Result()

        if not k8s.condition_true(manifest, COND_CREATED):
            self._set_condition(client, manifest, COND_CREATED,
                                "StudyJobCreated", f"study {study} registered")
            manifest = client.get(STUDYJOB_API_VERSION, STUDYJOB_KIND, ns, name)
            status = manifest.setdefault("status", {})

        trials: list[dict] = status.get("trials", []) or []

        # 1. sync trial states from worker jobs; collect objectives
        pending_collect = False
        for t in trials:
            if t["status"] in (T_SUCCEEDED, T_FAILED):
                continue
            job = client.get_or_none(_JOB_API[t["kind"]], t["kind"], ns,
                                     t["name"])
            if job is None:
                t["status"] = T_FAILED
                t["message"] = "worker job disappeared"
                state.engine.observe_failure(
                    state.params.get(t["name"], t.get("parameters", {})))
                continue
            if k8s.condition_true(job, COND_FAILED):
                t["status"] = T_FAILED
                self.vizier.set_trial_status(study, t["name"], T_FAILED)
                state.engine.observe_failure(
                    state.params.get(t["name"], t.get("parameters", {})))
            elif k8s.condition_true(job, COND_SUCCEEDED):
                done = self._collect(client, study, ns, t, state, job)
                pending_collect = pending_collect or not done
            elif k8s.condition_true(job, COND_RUNNING):
                t["status"] = T_RUNNING
                self.vizier.set_trial_status(study, t["name"], T_RUNNING)

        max_trials = self._max_trials(spec, state.engine)
        max_failed = int(spec.get("maxFailedTrials", max_trials or 1 << 30))
        n_failed = sum(1 for t in trials if t["status"] == T_FAILED)
        n_done = sum(1 for t in trials if t["status"] in (T_SUCCEEDED, T_FAILED))
        outstanding = len(trials) - n_done

        # 2. schedule the next batch once the current round has drained
        created = 0
        if outstanding == 0 and not pending_collect and \
                n_failed <= max_failed and \
                (max_trials is None or len(trials) < max_trials):
            request = int((spec.get("suggestionSpec") or {})
                          .get("requestNumber", 3))
            if max_trials is not None:
                request = min(request, max_trials - len(trials))
            assignments = state.engine.suggest(request) if request > 0 else []
            for assignment in assignments:
                trial = self._spawn_trial(client, manifest, study, assignment,
                                          state)
                trials.append(trial)
                created += 1

        # 3. roll up status + completion
        n_failed = sum(1 for t in trials if t["status"] == T_FAILED)
        n_done = sum(1 for t in trials if t["status"] in (T_SUCCEEDED, T_FAILED))
        status["trials"] = trials
        status["trialsTotal"] = len(trials)
        status["trialsRunning"] = len(trials) - n_done
        status["trialsSucceeded"] = n_done - n_failed
        status["trialsFailed"] = n_failed
        best = self.vizier.best_trial(study)
        if best is not None:
            status["bestTrial"] = {"name": best.name,
                                   "parameters": best.parameters,
                                   "objective": best.objective}

        if n_failed > max_failed:
            self._finish(client, manifest, COND_FAILED, "TrialsFailed",
                         f"{n_failed} trials failed (max {max_failed})",
                         status)
            return Result()

        exhausted = state.engine.exhausted() or \
            (max_trials is not None and len(trials) >= max_trials)
        if n_done == len(trials) and created == 0 and not pending_collect \
                and exhausted:
            if status.get("trialsSucceeded", 0) == 0:
                self._finish(client, manifest, COND_FAILED, "NoSuccessfulTrial",
                             "all trials failed", status)
            else:
                msg = (f"best trial {best.name} objective {best.objective}"
                       if best else "completed")
                self._finish(client, manifest, COND_SUCCEEDED,
                             "StudyCompleted", msg, status)
            return Result()

        if status_snapshot(status) != status_before:
            self._write_status(client, manifest, status)
        if not k8s.condition_true(manifest, COND_RUNNING) and trials:
            self._set_condition(client, manifest, COND_RUNNING,
                                "TrialsRunning", "trials in progress")
        return Result(requeue_after=0.05) if pending_collect else Result()

    # -- pieces --------------------------------------------------------------

    def _max_trials(self, spec: dict, engine: Suggestion) -> Optional[int]:
        if spec.get("maxTrials") is not None:
            return int(spec["maxTrials"])
        algo = ((spec.get("suggestionSpec") or {})
                .get("suggestionAlgorithm", "random")).lower()
        # grid/hyperband carry their own termination; open-ended samplers
        # need a budget (katib v1alpha1 used requestcount rounds; we default
        # to 4 rounds of requestNumber)
        if algo in ("grid", "hyperband"):
            return None
        request = int((spec.get("suggestionSpec") or {})
                      .get("requestNumber", 3))
        return 4 * request

    def _collect(self, client: KubeClient, study: str, ns: str, trial: dict,
                 state: _StudyState, job: dict) -> bool:
        """Objective collection, in priority order: vizier observation →
        <trial>-metrics ConfigMap → observation annotation on the worker job.
        Returns True when the trial reached a terminal collection state."""
        name = trial["name"]
        value = self.vizier.objective_of(study, name)
        if value is None:
            cm = client.get_or_none("v1", "ConfigMap", ns, f"{name}-metrics")
            if cm is not None:
                for metric, raw in (cm.get("data") or {}).items():
                    try:
                        self.vizier.report(study, name, metric, float(raw))
                    except ValueError:
                        continue
                value = self.vizier.objective_of(study, name)
        if value is None:
            raw = k8s.annotations_of(job).get(OBSERVATION_ANNOTATION)
            if raw:
                try:
                    import json as _json
                    obs = _json.loads(raw)
                    for metric, v in obs.items():
                        self.vizier.report(study, name, metric, float(v))
                    value = self.vizier.objective_of(study, name)
                except (ValueError, AttributeError):
                    pass
        if value is None:
            n = state.collect_retries.get(name, 0) + 1
            state.collect_retries[name] = n
            if n < self.max_collect_retries:
                return False  # requeue; metrics may still be in flight
            trial["status"] = T_FAILED
            trial["message"] = "objective metrics unavailable"
            self.vizier.set_trial_status(study, name, T_FAILED)
            state.engine.observe_failure(
                state.params.get(name, trial.get("parameters", {})))
            return True
        trial["status"] = T_SUCCEEDED
        trial["objective"] = value
        self.vizier.set_trial_status(study, name, T_SUCCEEDED)
        rec = self.vizier.get_study(study).trials.get(name)
        if rec is not None:
            rec.objective = value
        state.engine.observe(state.params.get(name, trial.get("parameters", {})),
                             state.sign * value)
        return True

    def _spawn_trial(self, client: KubeClient, manifest: dict, study: str,
                     assignment: dict[str, Any], state: _StudyState) -> dict:
        ns = k8s.namespace_of(manifest, "default")
        name = k8s.name_of(manifest)
        trial_name = f"{name}-trial-{state.next_index}"
        state.next_index += 1
        state.params[trial_name] = dict(assignment)

        spec = manifest.get("spec", {})
        worker = spec.get("workerSpec", {}) or {}
        import copy as _copy
        job = _copy.deepcopy(worker["template"])
        kind = job.get("kind", "TPUJob")
        if kind not in JOB_KINDS:
            raise ValueError(f"workerSpec.template kind {kind!r} not one of "
                             f"{JOB_KINDS}")
        job.setdefault("apiVersion", _JOB_API[kind])
        meta = job.setdefault("metadata", {})
        meta["name"] = trial_name
        meta["namespace"] = ns
        labels = meta.setdefault("labels", {})
        labels[STUDY_LABEL] = name
        labels[TRIAL_LABEL] = trial_name

        # $(param.<name>) / $(trialName) placeholders, then the katib
        # flag-append idiom unless disabled
        subs = {"trialName": trial_name, "studyName": study}
        for pname, v in assignment.items():
            subs[f"param.{pname.lstrip('-')}"] = v
        job = k8s.substitute_params(job, subs)
        if worker.get("injectParameters", True):
            _inject_args(job, assignment)

        env = {STUDY_ENV: study, TRIAL_ENV: trial_name}
        if self.vizier_url:
            env[VIZIER_URL_ENV] = self.vizier_url
        _inject_env(job, env)

        k8s.set_owner(job, manifest)
        client.create(job)
        self.vizier.register_trial(study, trial_name, dict(assignment))
        return {"name": trial_name, "kind": kind, "status": T_PENDING,
                "parameters": dict(assignment), "objective": None}

    # -- status plumbing -----------------------------------------------------

    def _write_status(self, client: KubeClient, manifest: dict,
                      status: dict) -> None:
        fresh = client.get(STUDYJOB_API_VERSION, STUDYJOB_KIND,
                           k8s.namespace_of(manifest, "default"),
                           k8s.name_of(manifest))
        merged = dict(fresh.get("status", {}))
        merged.update({k: v for k, v in status.items() if k != "conditions"})
        fresh["status"] = merged
        client.update_status(fresh)

    def _set_condition(self, client: KubeClient, manifest: dict, ctype: str,
                       reason: str, message: str) -> None:
        fresh = client.get(STUDYJOB_API_VERSION, STUDYJOB_KIND,
                           k8s.namespace_of(manifest, "default"),
                           k8s.name_of(manifest))
        k8s.set_condition(fresh, k8s.Condition(ctype, "True", reason, message))
        client.update_status(fresh)

    def _finish(self, client: KubeClient, manifest: dict, ctype: str,
                reason: str, message: str,
                status: Optional[dict] = None) -> None:
        if status is not None:
            self._write_status(client, manifest, status)
        self._set_condition(client, manifest, ctype, reason, message)
        log.info("studyjob %s/%s finished: %s (%s)",
                 k8s.namespace_of(manifest, "default"), k8s.name_of(manifest),
                 ctype, message)
