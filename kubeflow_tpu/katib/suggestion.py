"""Suggestion algorithms: random, grid, hyperband, bayesian optimization.

The reference runs each algorithm as a separate gRPC "suggestion service"
deployed per-algorithm (kubeflow/katib/suggestion.libsonnet:50-66; the four
algorithms in kubeflow/katib/prototypes/all.jsonnet:6-9). Here they are
in-process engines behind one interface; the StudyJob controller calls them
directly, and the vizier HTTP service exposes them for out-of-process use.

Parameter configs mirror StudyJob ``parameterconfigs``
(kubeflow/examples/prototypes/katib-studyjob-test-v1alpha1.jsonnet):
``{name, parametertype: double|int|discrete|categorical, feasible:
{min, max, list}}``.
"""

from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

SUGGESTION_ALGORITHMS = ("random", "grid", "hyperband", "bayesianoptimization")

DOUBLE = "double"
INT = "int"
DISCRETE = "discrete"
CATEGORICAL = "categorical"


@dataclass
class ParameterConfig:
    name: str
    parametertype: str = DOUBLE
    min: Optional[float] = None
    max: Optional[float] = None
    list: Optional[list] = None  # discrete / categorical values

    @classmethod
    def from_dict(cls, d: dict) -> "ParameterConfig":
        feasible = d.get("feasible", {}) or {}
        ptype = d.get("parametertype", DOUBLE).lower()
        lo = feasible.get("min")
        hi = feasible.get("max")
        return cls(
            name=d["name"], parametertype=ptype,
            min=float(lo) if lo is not None else None,
            max=float(hi) if hi is not None else None,
            list=feasible.get("list"),
        )

    def validate(self) -> None:
        if self.parametertype in (DOUBLE, INT):
            if self.min is None or self.max is None or self.min > self.max:
                raise ValueError(
                    f"parameter {self.name}: {self.parametertype} needs "
                    f"feasible min <= max, got [{self.min}, {self.max}]")
        elif self.parametertype in (DISCRETE, CATEGORICAL):
            if not self.list:
                raise ValueError(
                    f"parameter {self.name}: {self.parametertype} needs a "
                    f"non-empty feasible list")
        else:
            raise ValueError(f"parameter {self.name}: unknown parametertype "
                             f"{self.parametertype!r}")

    # -- numeric embedding (for the GP): value <-> [0,1] ---------------------

    def dims(self) -> int:
        """Embedding width: 1 for numeric/discrete, one-hot for categorical."""
        return len(self.list) if self.parametertype == CATEGORICAL else 1

    def encode(self, value: Any) -> list[float]:
        if self.parametertype == CATEGORICAL:
            onehot = [0.0] * len(self.list)
            onehot[self.list.index(value)] = 1.0
            return onehot
        if self.parametertype == DISCRETE:
            vals = [float(v) for v in self.list]
            lo, hi = min(vals), max(vals)
            span = (hi - lo) or 1.0
            return [(float(value) - lo) / span]
        span = (self.max - self.min) or 1.0
        return [(float(value) - self.min) / span]

    def sample(self, rng: random.Random) -> Any:
        if self.parametertype == DOUBLE:
            return rng.uniform(self.min, self.max)
        if self.parametertype == INT:
            return rng.randint(int(self.min), int(self.max))
        return rng.choice(self.list)

    def grid(self, n: int) -> list:
        if self.parametertype in (DISCRETE, CATEGORICAL):
            return [v for v in self.list]
        if self.parametertype == INT:
            lo, hi = int(self.min), int(self.max)
            count = min(n, hi - lo + 1)
            if count <= 1:
                return [lo]
            return sorted({round(lo + i * (hi - lo) / (count - 1))
                           for i in range(count)})
        if n <= 1:
            return [(self.min + self.max) / 2.0]
        step = (self.max - self.min) / (n - 1)
        return [self.min + i * step for i in range(n)]


def parse_parameter_configs(raw: list[dict]) -> list[ParameterConfig]:
    configs = [ParameterConfig.from_dict(d) for d in raw or []]
    for c in configs:
        c.validate()
    return configs


class Suggestion:
    """One study's suggestion engine.

    ``suggest(n)`` returns up to n parameter assignments (fewer when the
    space or schedule is exhausted); ``observe(params, value)`` feeds back a
    completed trial's objective, already sign-normalized so that HIGHER is
    always better (the caller negates for minimize studies).
    """

    def __init__(self, params: list[ParameterConfig], seed: int = 0,
                 settings: Optional[dict] = None):
        self.params = params
        self.rng = random.Random(seed)
        self.settings = settings or {}
        self.observations: list[tuple[dict, float]] = []

    def suggest(self, n: int) -> list[dict[str, Any]]:
        raise NotImplementedError

    def observe(self, trial_params: dict, value: float) -> None:
        self.observations.append((dict(trial_params), value))

    def observe_failure(self, trial_params: dict) -> None:
        """A trial failed with no objective. Default: drop it (random/grid/
        bayesian draw fresh points anyway); schedule-driven engines override
        so their pending queues drain instead of re-suggesting the config."""

    def exhausted(self) -> bool:
        return False


class RandomSuggestion(Suggestion):
    def suggest(self, n: int) -> list[dict[str, Any]]:
        return [{p.name: p.sample(self.rng) for p in self.params}
                for _ in range(n)]


class GridSuggestion(Suggestion):
    """Cartesian product; per-param point count from suggestion parameters
    (``DefaultGrid`` / ``grid_<name>``), katib grid-suggestion semantics."""

    def __init__(self, params, seed=0, settings=None):
        super().__init__(params, seed, settings)
        default_n = int(self.settings.get("DefaultGrid", 3))
        axes = [p.grid(int(self.settings.get(f"grid_{p.name}", default_n)))
                for p in self.params]
        self._points = [
            {p.name: v for p, v in zip(self.params, combo)}
            for combo in itertools.product(*axes)
        ]
        self._cursor = 0

    def suggest(self, n: int) -> list[dict[str, Any]]:
        batch = self._points[self._cursor:self._cursor + n]
        self._cursor += len(batch)
        return batch

    def exhausted(self) -> bool:
        return self._cursor >= len(self._points)


@dataclass
class _Bracket:
    s: int
    n: int            # configs in the first round
    r: float          # resource per config in the first round
    rounds_left: int = 0
    pending: list = field(default_factory=list)     # awaiting results
    results: list = field(default_factory=list)     # (params, value)
    configs: list = field(default_factory=list)     # current round's configs


class HyperbandSuggestion(Suggestion):
    """Hyperband (successive halving over brackets).

    Settings: ``eta`` (down-sampling rate, default 3), ``r_l`` (max resource,
    default 81), ``resourceName`` (the parameter that carries the per-trial
    budget, e.g. ``--epochs``). Mirrors katib's hyperband suggestion
    parameters (eta / r_l / ResourceName).
    """

    def __init__(self, params, seed=0, settings=None):
        super().__init__(params, seed, settings)
        self.eta = float(self.settings.get("eta", 3))
        self.R = float(self.settings.get("r_l", 81))
        self.resource_name = self.settings.get("resourceName", "--budget")
        s_max = int(math.floor(math.log(self.R) / math.log(self.eta)))
        self._brackets: list[_Bracket] = []
        for s in range(s_max, -1, -1):
            n = int(math.ceil((s_max + 1) / (s + 1) * self.eta ** s))
            r = self.R * self.eta ** (-s)
            self._brackets.append(_Bracket(s=s, n=n, r=r, rounds_left=s + 1))
        self._bracket_i = 0
        self._prepare_round(fresh=True)

    # -- schedule ------------------------------------------------------------

    def _bracket(self) -> Optional[_Bracket]:
        if self._bracket_i >= len(self._brackets):
            return None
        return self._brackets[self._bracket_i]

    def _prepare_round(self, fresh: bool) -> None:
        b = self._bracket()
        if b is None:
            return
        if fresh:
            # new bracket: n random configs at resource r
            b.configs = [
                ({p.name: p.sample(self.rng) for p in self.params}, b.r)
                for _ in range(b.n)
            ]
        b.pending = [c for c in b.configs]
        b.results = []

    def _advance_if_round_done(self) -> None:
        b = self._bracket()
        if b is None or b.pending or not b.configs:
            return
        b.rounds_left -= 1
        keep = int(math.floor(len(b.results) / self.eta))
        if b.rounds_left <= 0 or keep < 1:
            self._bracket_i += 1
            self._prepare_round(fresh=True)
            return
        survivors = sorted(b.results, key=lambda t: t[1], reverse=True)[:keep]
        next_r = min(b.configs[0][1] * self.eta, self.R)
        b.configs = [(dict(p), next_r) for (p, _v) in survivors]
        self._prepare_round(fresh=False)

    # -- interface -----------------------------------------------------------

    def suggest(self, n: int) -> list[dict[str, Any]]:
        b = self._bracket()
        if b is None:
            return []
        out = []
        for params, r in b.pending[:n]:
            assignment = dict(params)
            budget = int(round(r)) if float(r).is_integer() or r >= 1 else r
            assignment[self.resource_name] = budget
            out.append(assignment)
        return out

    def observe(self, trial_params: dict, value: float) -> None:
        super().observe(trial_params, value)
        self._settle(trial_params, value)

    def observe_failure(self, trial_params: dict) -> None:
        # settle as worst-possible so the round drains and the config is
        # never promoted (it still counts toward the round's population)
        self._settle(trial_params, float("-inf"))

    def _settle(self, trial_params: dict, value: float) -> None:
        b = self._bracket()
        if b is None:
            return
        bare = {k: v for k, v in trial_params.items()
                if k != self.resource_name}
        for i, (params, _r) in enumerate(b.pending):
            if params == bare:
                b.pending.pop(i)
                b.results.append((params, value))
                break
        self._advance_if_round_done()

    def exhausted(self) -> bool:
        return self._bracket() is None


class BayesianOptimizationSuggestion(Suggestion):
    """GP (RBF kernel) + expected-improvement acquisition, numpy only.

    Settings: ``burn_in`` random trials before the GP engages (default 4),
    ``length_scale`` (default 0.3), ``noise`` (default 1e-6), ``candidates``
    (acquisition sampling budget, default 256).
    """

    def __init__(self, params, seed=0, settings=None):
        super().__init__(params, seed, settings)
        self.burn_in = int(self.settings.get("burn_in", 4))
        self.length_scale = float(self.settings.get("length_scale", 0.3))
        self.noise = float(self.settings.get("noise", 1e-6))
        self.n_candidates = int(self.settings.get("candidates", 256))

    def _encode(self, assignment: dict) -> np.ndarray:
        vec: list[float] = []
        for p in self.params:
            vec.extend(p.encode(assignment[p.name]))
        return np.asarray(vec)

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / self.length_scale ** 2)

    def _ei(self, cand: np.ndarray, x: np.ndarray, y: np.ndarray) -> np.ndarray:
        y_mean, y_std = y.mean(), y.std() or 1.0
        yn = (y - y_mean) / y_std
        k_xx = self._kernel(x, x) + self.noise * np.eye(len(x))
        k_cx = self._kernel(cand, x)
        try:
            chol = np.linalg.cholesky(k_xx)
        except np.linalg.LinAlgError:
            chol = np.linalg.cholesky(k_xx + 1e-4 * np.eye(len(x)))
        alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, yn))
        mu = k_cx @ alpha
        v = np.linalg.solve(chol, k_cx.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        sigma = np.sqrt(var)
        best = yn.max()
        z = (mu - best) / sigma
        # EI = sigma * (z*Phi(z) + phi(z))
        phi = np.exp(-0.5 * z ** 2) / math.sqrt(2 * math.pi)
        Phi = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2)))
        return sigma * (z * Phi + phi)

    def suggest(self, n: int) -> list[dict[str, Any]]:
        out: list[dict[str, Any]] = []
        random_engine = RandomSuggestion(self.params, seed=self.rng.random())
        if len(self.observations) < self.burn_in:
            return random_engine.suggest(n)
        x = np.stack([self._encode(p) for p, _ in self.observations])
        y = np.asarray([v for _, v in self.observations])
        for _ in range(n):
            cands = random_engine.suggest(self.n_candidates)
            cand_x = np.stack([self._encode(c) for c in cands])
            ei = self._ei(cand_x, x, y)
            best = cands[int(np.argmax(ei))]
            out.append(best)
            # pessimistic fantasy so a batch doesn't collapse to one point
            x = np.concatenate([x, self._encode(best)[None]], 0)
            y = np.concatenate([y, [y.min()]])
        return out


_ALGORITHMS = {
    "random": RandomSuggestion,
    "grid": GridSuggestion,
    "hyperband": HyperbandSuggestion,
    "bayesianoptimization": BayesianOptimizationSuggestion,
}


def make_suggestion(algorithm: str, params: list[ParameterConfig],
                    seed: int = 0,
                    settings: Optional[dict] = None) -> Suggestion:
    algo = (algorithm or "random").lower().replace("-", "").replace("_", "")
    if algo not in _ALGORITHMS:
        raise ValueError(
            f"unknown suggestion algorithm {algorithm!r}; "
            f"supported: {sorted(_ALGORITHMS)}")
    return _ALGORITHMS[algo](params, seed=seed, settings=settings)
