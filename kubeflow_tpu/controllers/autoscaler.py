"""Metrics-driven serving autoscaler (ISSUE 18).

PR 11 built the signals (`/healthz?verbose=1` + `/metrics`: queue
depth, oldest-waiting age, SLO burn rates), PR 9 built the warm pods
(~1 s first inference off the AOT/compile-cache ladder), PR 12 built
the actuators (fleet ``add_replica`` for scale-up, graceful drain for
zero-loss scale-down) — but nothing consumed the signals: replica
count was static configuration. This module closes the loop, the
"plan scaling actions on measured signals" pattern from the dynamic
MPI-scheduling line of work (PAPERS.md):

- **AutoscalerPolicy** — the pure decision core (clock injected, no
  I/O): asymmetric hysteresis. Scale-UP is fast — one poll over the
  burn-rate / queue-depth / oldest-wait thresholds is a paying user
  waiting, act now. Scale-DOWN is slow — the whole fleet must be
  *sustainedly* idle (``idleDownSeconds``) before a replica is
  drained; a momentary lull must not shed capacity a burst will want
  back. A shared ``cooldownSeconds`` after ANY scale event means the
  policy can never flap against the drain it just started.
- **FleetAutoscaler** — the live control loop over a FleetRouter:
  polls every replica's verbose healthz, feeds the policy, scales up
  by launching onto a warm pod + ``router.add_replica`` and down by
  graceful drain (`POST /drain`, zero-loss asserted by the bench)
  then ``router.remove_replica``. ``bench.py --mode autoscaler``
  drives it.
- **ServingFleetReconciler** — the controller-manager face: reconciles
  ``ServingFleet`` objects (rendered by ``manifests/serving.py``
  ``tpu_serving(autoscale=True)``), registered as ``autoscaler`` in
  ``controllers/__main__.py`` so it runs under the PR 14
  leader-election/fencing machinery like every other controller.

Every scale event lands on the trace (component ``autoscaler``, the
KFTPU_SPAN_PATH contract) and in the ``kftpu_autoscaler_*`` gauges.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.request
from dataclasses import dataclass
from typing import Callable, Optional

from ..obs import registry as obsreg
from ..obs import trace as obstrace
from .runtime import Key, Reconciler, Result, status_snapshot

log = logging.getLogger(__name__)

SERVING_FLEET_KIND = "ServingFleet"
SERVING_FLEET_API_VERSION = "kubeflow.org/v1alpha1"


# --------------------------------------------------------------- signals


@dataclass
class ReplicaSignals:
    """One replica's scaling-relevant slice of the verbose healthz
    payload (serving/replica_state.py snapshot())."""

    name: str = ""
    queue_depth: int = 0          # sum over models: waiting, NOT admitted
    oldest_wait_s: float = 0.0    # max over models
    inflight: int = 0             # sum over models
    burn_fast: float = 0.0        # max 60s-window burn (latency|availability)
    draining: bool = False

    @classmethod
    def from_snapshot(cls, name: str, snap: dict) -> "ReplicaSignals":
        qdepth = inflight = 0
        oldest = burn = 0.0
        for m in snap.get("models", []):
            qdepth += int(m.get("queueDepth", 0) or 0)
            inflight += int(m.get("inFlight", 0) or 0)
            oldest = max(oldest,
                         float(m.get("oldestWaitSeconds", 0.0) or 0.0))
            fast = (m.get("burnRates") or {}).get("60s") or {}
            for v in fast.values():
                burn = max(burn, float(v or 0.0))
        return cls(name=name, queue_depth=qdepth, oldest_wait_s=oldest,
                   inflight=inflight, burn_fast=burn,
                   draining=bool(snap.get("draining")))


def fetch_signals(name: str, base_url: str,
                  timeout_s: float = 1.0) -> Optional[ReplicaSignals]:
    """Poll one replica's ``/healthz?verbose=1``; None when
    unreachable (an unpollable replica is neither pressure nor idle —
    the policy treats missing data conservatively)."""
    try:
        with urllib.request.urlopen(f"{base_url}/healthz?verbose=1",
                                    timeout=timeout_s) as resp:
            return ReplicaSignals.from_snapshot(name, json.loads(resp.read()))
    except Exception:  # noqa: BLE001 — poll failure is a signal, not a crash
        return None


# ---------------------------------------------------------------- config


@dataclass
class AutoscalerConfig:
    """The knob set the ServingFleet manifest carries
    (``spec.autoscaler``) and the CLI/reconciler consume. camelCase
    keys to match the manifest surface; ``from_dict`` fails loudly on
    typos (the BreakerConfig pattern)."""

    min_replicas: int = 1
    max_replicas: int = 4
    # scale-up triggers (fast path — any one over threshold fires)
    burn_up_threshold: float = 2.0       # 60s-window SLO burn rate
    queue_up_threshold: float = 4.0      # mean queue depth per live replica
    oldest_wait_up_s: float = 0.5        # oldest queued request's age
    # scale-down trigger (slow path — ALL replicas idle this long)
    idle_down_s: float = 300.0
    # flap guard: no second scale event inside this window
    cooldown_s: float = 60.0
    poll_interval_s: float = 5.0

    KEYS = ("minReplicas", "maxReplicas", "burnUpThreshold",
            "queueUpThreshold", "oldestWaitUpSeconds",
            "idleDownSeconds", "cooldownSeconds", "pollIntervalSeconds")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "AutoscalerConfig":
        d = dict(d or {})
        unknown = set(d) - set(cls.KEYS)
        if unknown:
            # a typo'd knob must fail loudly, not silently default
            raise ValueError(
                f"unknown autoscaler config keys {sorted(unknown)}; "
                f"valid: {list(cls.KEYS)}")
        return cls(
            min_replicas=int(d.get("minReplicas", 1)),
            max_replicas=int(d.get("maxReplicas", 4)),
            burn_up_threshold=float(d.get("burnUpThreshold", 2.0)),
            queue_up_threshold=float(d.get("queueUpThreshold", 4.0)),
            oldest_wait_up_s=float(d.get("oldestWaitUpSeconds", 0.5)),
            idle_down_s=float(d.get("idleDownSeconds", 300.0)),
            cooldown_s=float(d.get("cooldownSeconds", 60.0)),
            poll_interval_s=float(d.get("pollIntervalSeconds", 5.0)))

    def to_dict(self) -> dict:
        return {"minReplicas": self.min_replicas,
                "maxReplicas": self.max_replicas,
                "burnUpThreshold": self.burn_up_threshold,
                "queueUpThreshold": self.queue_up_threshold,
                "oldestWaitUpSeconds": self.oldest_wait_up_s,
                "idleDownSeconds": self.idle_down_s,
                "cooldownSeconds": self.cooldown_s,
                "pollIntervalSeconds": self.poll_interval_s}


# ---------------------------------------------------------------- policy


@dataclass
class Decision:
    direction: Optional[str]  # "up" | "down" | None
    reason: str = ""


class AutoscalerPolicy:
    """Pure hysteresis core: feed it signals + a clock, get a
    direction. Holds only the temporal state hysteresis needs
    (last-scale time for the cooldown, idle-since for the sustained-
    idle window); everything else is recomputed from this poll's
    signals — restart-safe by construction."""

    def __init__(self, config: Optional[AutoscalerConfig] = None):
        self.config = config or AutoscalerConfig()
        self._last_scale_t: Optional[float] = None
        self._idle_since: Optional[float] = None

    def in_cooldown(self, now: float) -> bool:
        return (self._last_scale_t is not None and
                now - self._last_scale_t < self.config.cooldown_s)

    def decide(self, signals: list[Optional[ReplicaSignals]],
               replicas: int, now: float) -> Decision:
        cfg = self.config
        live = [s for s in signals if s is not None and not s.draining]
        qdepth = sum(s.queue_depth for s in live)
        oldest = max((s.oldest_wait_s for s in live), default=0.0)
        burn = max((s.burn_fast for s in live), default=0.0)
        inflight = sum(s.inflight for s in live)
        mean_q = qdepth / max(1, len(live))

        pressure = []
        if burn >= cfg.burn_up_threshold:
            pressure.append(f"burn {burn:.1f}≥{cfg.burn_up_threshold:g}")
        if mean_q >= cfg.queue_up_threshold:
            pressure.append(
                f"queue {mean_q:.1f}≥{cfg.queue_up_threshold:g}/replica")
        if oldest >= cfg.oldest_wait_up_s:
            pressure.append(
                f"oldest wait {oldest:.2f}s≥{cfg.oldest_wait_up_s:g}s")

        if pressure:
            # fast path: pressure is a user waiting — but never inside
            # the cooldown (the capacity we just added, or the drain we
            # just started, has not settled yet)
            self._idle_since = None
            if replicas >= cfg.max_replicas:
                return Decision(None, "pressure but at maxReplicas")
            if self.in_cooldown(now):
                return Decision(None, "pressure but in cooldown")
            self._last_scale_t = now
            return Decision("up", "; ".join(pressure))

        # unpollable replicas block scale-down: missing data must read
        # as "unknown load", never as idle capacity to shed
        all_polled = len(live) == replicas and replicas > 0
        idle = (all_polled and qdepth == 0 and inflight == 0
                and burn < 1.0)
        if not idle:
            self._idle_since = None
            return Decision(None, "steady")
        if self._idle_since is None:
            self._idle_since = now
        idle_for = now - self._idle_since
        if replicas <= cfg.min_replicas:
            return Decision(None, "idle but at minReplicas")
        if idle_for < cfg.idle_down_s:
            return Decision(
                None, f"idle {idle_for:.0f}s < {cfg.idle_down_s:g}s")
        if self.in_cooldown(now):
            return Decision(None, "idle but in cooldown")
        self._last_scale_t = now
        # the next scale-down needs a full fresh idle window — one
        # long lull drains one replica, not the whole fleet at once
        self._idle_since = now
        return Decision("down", f"fleet idle {idle_for:.0f}s")


# --------------------------------------------------------------- metrics


class _AutoscalerMetrics:
    """kftpu_autoscaler_* on the default registry (the controller
    manager's /metrics surface), labeled by fleet."""

    def __init__(self):
        self.replicas = obsreg.gauge(
            "kftpu_autoscaler_replicas",
            "current fleet replica count", labels=("fleet",))
        self.desired = obsreg.gauge(
            "kftpu_autoscaler_desired_replicas",
            "replica count the policy wants", labels=("fleet",))
        self.events = obsreg.counter(
            "kftpu_autoscaler_scale_events_total",
            "scale actions taken", labels=("fleet", "direction"))
        self.cooldown = obsreg.gauge(
            "kftpu_autoscaler_cooldown_active",
            "1 while the flap-guard cooldown holds scaling",
            labels=("fleet",))

    def observe(self, fleet: str, replicas: int, desired: int,
                cooldown: bool) -> None:
        self.replicas.labels(fleet=fleet).set(replicas)
        self.desired.labels(fleet=fleet).set(desired)
        self.cooldown.labels(fleet=fleet).set(1 if cooldown else 0)


def _emit_scale_span(fleet: str, direction: str, replica: str,
                     reason: str, replicas: int) -> None:
    """Scale events ride the trace (KFTPU_SPAN_PATH contract) so a
    latency investigation can line capacity changes up against the
    request series."""
    tracer = obstrace.default_tracer("autoscaler")
    if tracer is None:
        return
    now = time.time()
    tracer.emit(f"autoscale-{direction}", start=now, end=now,
                trace_id=f"autoscaler-{fleet}", fleet=fleet,
                replica=replica, reason=reason, replicas=replicas)


# --------------------------------------------------- live fleet actuator


class FleetAutoscaler:
    """The closed loop over a live FleetRouter: poll → decide → act.

    ``launcher()`` must return ``(name, base_url)`` for a NEW replica —
    the warm-pod contract says it comes up with its model already
    loaded off the AOT/compile-cache ladder (``start_kind`` warm/aot),
    so its first inference is ~1–2 s away, not a cold XLA compile.
    ``stopper(name)`` tears a drained replica down. Scale-down is
    graceful by construction: ``POST /drain`` flushes the in-flight
    cohort and refuses new work BEFORE the replica leaves the router —
    the bench asserts the drain report shows zero loss.
    """

    def __init__(self, router,
                 launcher: Callable[[], tuple[str, str]],
                 stopper: Optional[Callable[[str], None]] = None,
                 config: Optional[AutoscalerConfig] = None,
                 fleet: str = "fleet",
                 clock: Callable[[], float] = time.monotonic,
                 poll_timeout_s: float = 1.0):
        self.router = router
        self.launcher = launcher
        self.stopper = stopper
        self.fleet = fleet
        self.clock = clock
        self.poll_timeout_s = poll_timeout_s
        self.policy = AutoscalerPolicy(config)
        self.replicas: dict[str, str] = {}   # name → base_url, add order
        self.events: list[dict] = []
        self._metrics = _AutoscalerMetrics()

    def adopt(self, name: str, base_url: str) -> None:
        """Register an already-running replica (the fleet's seed set)."""
        self.replicas[name] = base_url

    def step(self, now: Optional[float] = None) -> Decision:
        """One control iteration; returns the decision for the bench's
        event accounting."""
        now = self.clock() if now is None else now
        signals = [fetch_signals(n, u, timeout_s=self.poll_timeout_s)
                   for n, u in self.replicas.items()]
        decision = self.policy.decide(signals, len(self.replicas), now)
        desired = len(self.replicas) + (
            1 if decision.direction == "up"
            else -1 if decision.direction == "down" else 0)
        self._metrics.observe(self.fleet, len(self.replicas), desired,
                              self.policy.in_cooldown(now))
        if decision.direction == "up":
            self._scale_up(decision, now)
        elif decision.direction == "down":
            self._scale_down(decision, now)
        return decision

    def _scale_up(self, decision: Decision, now: float) -> None:
        name, url = self.launcher()
        self.replicas[name] = url
        self.router.add_replica(name, url)
        self._record("up", name, decision.reason, now)

    def _scale_down(self, decision: Decision, now: float) -> None:
        # LIFO victim: the most recently added non-draining replica —
        # the warm pool keeps its oldest (most-proven) members
        victim = next((n for n in reversed(list(self.replicas))), None)
        if victim is None:
            return
        url = self.replicas[victim]
        report = {}
        try:
            req = urllib.request.Request(f"{url}/drain", method="POST",
                                         data=b"")
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                report = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 — a dead replica is drained
            log.warning("autoscaler: drain of %s failed: %s", victim, e)
        self.router.remove_replica(victim)
        del self.replicas[victim]
        if self.stopper is not None:
            self.stopper(victim)
        self._record("down", victim, decision.reason, now,
                     drain_report=report)

    def _record(self, direction: str, replica: str, reason: str,
                now: float, **extra) -> None:
        self._metrics.events.labels(fleet=self.fleet,
                                    direction=direction).inc()
        _emit_scale_span(self.fleet, direction, replica, reason,
                         len(self.replicas))
        self.events.append({"direction": direction, "replica": replica,
                            "reason": reason, "t": now, **extra})
        log.info("autoscaler[%s]: scale-%s %s (%s) → %d replicas",
                 self.fleet, direction, replica, reason,
                 len(self.replicas))


# ------------------------------------------------------------ reconciler


class ServingFleetReconciler(Reconciler):
    """Controller-manager face of the autoscaler: level-triggered over
    ``ServingFleet`` objects. Each object's ``spec.autoscaler`` carries
    the AutoscalerConfig knobs; ``status.replicas`` is the live
    endpoint list (seeded from ``spec.endpoints``, then owned by this
    reconciler as it scales). Runs under the PR 14 leader-election/
    fencing machinery like every hosted controller — a deposed
    leader's scale action dies at the fenced client boundary.

    An ``actuator`` (the FleetAutoscaler launcher/stopper pair wrapped
    as ``scale_up() → {"name","url","startKind"}`` and
    ``scale_down(name)``) makes decisions real; without one the
    reconciler is declarative-only — it publishes
    ``status.desiredReplicas`` + conditions for an external actuator,
    the HPA-writes-the-scale-subresource shape.
    """

    primary = (SERVING_FLEET_API_VERSION, SERVING_FLEET_KIND)
    controller_name = "autoscaler"

    def __init__(self, actuator=None,
                 poller: Callable[..., Optional[ReplicaSignals]] =
                 fetch_signals,
                 clock: Callable[[], float] = time.monotonic):
        self.actuator = actuator
        self.poller = poller
        self.clock = clock
        # hysteresis state is per object and lives across reconciles
        self._policies: dict[Key, AutoscalerPolicy] = {}
        self._metrics = _AutoscalerMetrics()

    def reconcile(self, client, key: Key) -> Result:
        from ..cluster.client import NotFoundError
        ns, name = key
        try:
            obj = client.get(SERVING_FLEET_API_VERSION,
                             SERVING_FLEET_KIND, ns, name)
        except NotFoundError:
            self._policies.pop(key, None)
            return Result()
        spec = obj.get("spec", {}) or {}
        cfg = AutoscalerConfig.from_dict(spec.get("autoscaler"))
        policy = self._policies.get(key)
        if policy is None:
            policy = self._policies[key] = AutoscalerPolicy(cfg)
        else:
            policy.config = cfg  # spec edits apply next decision

        status = dict(obj.get("status", {}) or {})
        replicas = list(status.get("replicas") or
                        [{"name": f"{name}-{i}", "url": u}
                         for i, u in enumerate(spec.get("endpoints") or [])])
        now = self.clock()
        signals = [self.poller(r.get("name", ""), r.get("url", ""))
                   for r in replicas]
        decision = policy.decide(signals, len(replicas), now)

        desired = len(replicas) + (1 if decision.direction == "up"
                                   else -1 if decision.direction == "down"
                                   else 0)
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        if self.actuator is not None:
            if decision.direction == "up":
                rep = self.actuator.scale_up()
                replicas.append(rep)
                self._record(name, "up", rep.get("name", ""),
                             decision.reason, len(replicas))
            elif decision.direction == "down" and replicas:
                victim = replicas[-1]
                self.actuator.scale_down(victim.get("name", ""))
                replicas = replicas[:-1]
                self._record(name, "down", victim.get("name", ""),
                             decision.reason, len(replicas))

        self._metrics.observe(name, len(replicas), desired,
                              policy.in_cooldown(now))
        before = status_snapshot(status)
        status.update({"replicas": replicas,
                       "desiredReplicas": desired,
                       "observedReplicas": len(replicas)})
        if decision.direction:
            status["lastScale"] = {"direction": decision.direction,
                                   "reason": decision.reason}
        if status_snapshot(status) != before:
            fresh = client.get(SERVING_FLEET_API_VERSION,
                               SERVING_FLEET_KIND, ns, name)
            fresh["status"] = status
            client.update_status(fresh)
        return Result(requeue_after=cfg.poll_interval_s)

    def _record(self, fleet: str, direction: str, replica: str,
                reason: str, replicas: int) -> None:
        self._metrics.events.labels(fleet=fleet, direction=direction).inc()
        _emit_scale_span(fleet, direction, replica, reason, replicas)
        log.info("autoscaler[%s]: scale-%s %s (%s) → %d replicas",
                 fleet, direction, replica, reason, replicas)
