"""Profile controller: multi-tenancy namespaces.

The reference's profile-controller reconciles a Profile CR into a Namespace
+ ``default-editor``/``default-viewer`` ServiceAccounts + an owner
RoleBinding (components/profile-controller/pkg/controller/profile/
profile_controller.go:109-196, updateServiceAccount :204-209); the
access-management swagger (SURVEY.md §2.6) defines Profile = owner +
namespace. ResourceQuota support mirrors the metacontroller sync hook
(kubeflow/profiles/sync-profile.jsonnet:1-40).
"""

from __future__ import annotations

import logging

from ..api import k8s
from ..cluster.client import KubeClient, NotFoundError
from .runtime import Key, Reconciler, Result

log = logging.getLogger(__name__)

PROFILE_API_VERSION = "kubeflow.org/v1alpha1"
PROFILE_KIND = "Profile"
EDITOR_SA = "default-editor"
VIEWER_SA = "default-viewer"
OWNER_ANNOTATION = "owner"


class ProfileReconciler(Reconciler):
    primary = (PROFILE_API_VERSION, PROFILE_KIND)
    owns = [("v1", "Namespace"), ("v1", "ServiceAccount"),
            ("rbac.authorization.k8s.io/v1", "RoleBinding"),
            ("v1", "ResourceQuota")]

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        _, name = key
        try:
            profile = client.get(PROFILE_API_VERSION, PROFILE_KIND,
                                 key[0] or "default", name)
        except NotFoundError:
            return Result()
        spec = profile.get("spec", {})
        owner = (spec.get("owner") or {}).get("name", "")

        namespace = {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {
                "name": name,
                "labels": {"katib-metricscollector-injection": "enabled",
                           "serving.kubeflow.org/inferenceservice": "enabled",
                           "profile": name},
                "annotations": {OWNER_ANNOTATION: owner},
            },
        }
        k8s.set_owner(namespace, profile)
        client.apply(namespace)

        for sa in (EDITOR_SA, VIEWER_SA):
            obj = {"apiVersion": "v1", "kind": "ServiceAccount",
                   "metadata": {"name": sa, "namespace": name}}
            k8s.set_owner(obj, profile)
            client.apply(obj)

        bindings = [
            # the profile owner administers the namespace
            ("namespaceAdmin", "ClusterRole", "kubeflow-admin",
             [{"kind": (spec.get("owner") or {}).get("kind", "User"),
               "name": owner}]),
            ("default-editor", "ClusterRole", "kubeflow-edit",
             [{"kind": "ServiceAccount", "name": EDITOR_SA,
               "namespace": name}]),
            ("default-viewer", "ClusterRole", "kubeflow-view",
             [{"kind": "ServiceAccount", "name": VIEWER_SA,
               "namespace": name}]),
        ]
        for bname, role_kind, role, subjects in bindings:
            rb = {
                "apiVersion": "rbac.authorization.k8s.io/v1",
                "kind": "RoleBinding",
                "metadata": {"name": bname, "namespace": name},
                "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                            "kind": role_kind, "name": role},
                "subjects": subjects,
            }
            k8s.set_owner(rb, profile)
            client.apply(rb)

        if spec.get("resourceQuotaSpec"):
            quota = {
                "apiVersion": "v1", "kind": "ResourceQuota",
                "metadata": {"name": "kf-resource-quota", "namespace": name},
                "spec": spec["resourceQuotaSpec"],
            }
            k8s.set_owner(quota, profile)
            client.apply(quota)
        else:
            # prune: dropping resourceQuotaSpec must lift the quota, not
            # leave the old limit enforced forever
            try:
                client.delete("v1", "ResourceQuota", name,
                              "kf-resource-quota")
            except NotFoundError:
                pass

        if not k8s.condition_true(profile, "Ready"):
            fresh = client.get(PROFILE_API_VERSION, PROFILE_KIND,
                               key[0] or "default", name)
            k8s.set_condition(fresh, k8s.Condition(
                "Ready", "True", "ProfileProvisioned",
                f"namespace {name} provisioned for {owner}"))
            client.update_status(fresh)
        return Result()
