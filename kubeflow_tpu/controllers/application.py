"""Application controller: aggregate component health into conditions.

The reference deploys the Application CRD (app.k8s.io/v1beta1) with a
metacontroller CompositeController whose jsonnetd sync hook folds the
selected components' statuses into the Application's status
(kubeflow/application/application.libsonnet:213-228 sync hook, :16-41
CRD). Here the same aggregation is a native reconciler: spec.selector's
matchLabels + spec.componentKinds choose the components; per-kind health
rules roll up into status.components and a Ready condition.
"""

from __future__ import annotations

import logging

from ..api import k8s
from ..cluster.client import KubeClient, NotFoundError
from .runtime import Key, Reconciler, Result, status_snapshot

log = logging.getLogger(__name__)

APPLICATION_API_VERSION = "app.k8s.io/v1beta1"
APPLICATION_KIND = "Application"

# group → the apiVersion we watch/list that group's kinds at
_GROUP_VERSIONS = {
    "": "v1",
    "core": "v1",
    "apps": "apps/v1",
    "batch": "batch/v1",
    "kubeflow.org": "kubeflow.org/v1",
    "argoproj.io": "argoproj.io/v1alpha1",
}

# kinds watched for selector aggregation (bounded: watching every kind in
# the cluster is the metacontroller's job; these cover what the reference's
# packages deploy into Applications)
WATCHED_KINDS = [
    ("apps/v1", "Deployment"),
    ("apps/v1", "StatefulSet"),
    ("v1", "Service"),
    ("batch/v1", "Job"),
]


def _component_ready(obj: dict) -> tuple[bool, str]:
    """Per-kind health rule (the kube app controller's heuristics)."""
    kind = obj.get("kind", "")
    status = obj.get("status", {}) or {}
    spec = obj.get("spec", {}) or {}
    if kind in ("Deployment", "StatefulSet"):
        want = int(spec.get("replicas", 1))
        have = int(status.get("readyReplicas", 0))
        return have >= want, f"{have}/{want} ready"
    if kind == "Job":
        if status.get("succeeded"):
            return True, "succeeded"
        if status.get("failed"):
            return False, "failed"
        return False, "running"
    if kind == "Pod":
        phase = status.get("phase", "Pending")
        return phase in ("Running", "Succeeded"), phase.lower()
    conditions = {c.get("type"): c.get("status")
                  for c in status.get("conditions", []) or []}
    if conditions:
        for ctype in ("Ready", "Available", "Succeeded"):
            if ctype in conditions:
                return conditions[ctype] == "True", f"{ctype}={conditions[ctype]}"
    # existence is the only signal for plain kinds (Service, ConfigMap)
    return True, "exists"


def _selector_matches(selector: dict, labels: dict) -> bool:
    match = (selector or {}).get("matchLabels") or {}
    return bool(match) and all(labels.get(k) == v for k, v in match.items())


class ApplicationReconciler(Reconciler):
    primary = (APPLICATION_API_VERSION, APPLICATION_KIND)
    owns = list(WATCHED_KINDS)

    def map_event(self, client: KubeClient, obj: dict) -> list[Key]:
        """A component changed: requeue every Application whose selector
        matches its labels (the sync-hook trigger shape)."""
        labels = obj.get("metadata", {}).get("labels") or {}
        ns = k8s.namespace_of(obj, "default")
        keys = []
        for app in client.list(APPLICATION_API_VERSION, APPLICATION_KIND, ns):
            if _selector_matches(app.get("spec", {}).get("selector"), labels):
                keys.append((ns, k8s.name_of(app)))
        return keys

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            app = client.get(APPLICATION_API_VERSION, APPLICATION_KIND,
                             ns, name)
        except NotFoundError:
            return Result()
        spec = app.get("spec", {}) or {}
        selector = spec.get("selector") or {}
        kinds = spec.get("componentKinds") or []

        components = []
        ready_all = True
        for ck in kinds:
            group = ck.get("group", "") or ""
            kind = ck.get("kind", "")
            api_version = _GROUP_VERSIONS.get(group, group and f"{group}/v1"
                                              or "v1")
            try:
                objs = client.list(api_version, kind, ns)
            except Exception:  # noqa: BLE001 - kind not served yet
                objs = []
            for obj in objs:
                labels = obj.get("metadata", {}).get("labels") or {}
                if not _selector_matches(selector, labels):
                    continue
                ok, why = _component_ready(obj)
                ready_all = ready_all and ok
                components.append({
                    "group": group, "kind": kind,
                    "name": k8s.name_of(obj),
                    "status": "Ready" if ok else "NotReady",
                    "reason": why,
                })
        if not components:
            ready_all = False

        status = dict(app.get("status", {}))
        before = status_snapshot(status)
        status["observedGeneration"] = app.get("metadata", {}).get(
            "generation", 0)
        status["componentsReady"] = (
            f"{sum(1 for c in components if c['status'] == 'Ready')}"
            f"/{len(components)}")
        status["components"] = components
        k8s.set_condition(app, k8s.Condition(
            "Ready", "True" if ready_all else "False",
            "ComponentsReady" if ready_all else "ComponentsNotReady",
            status["componentsReady"] + " components ready"))
        status["conditions"] = app["status"].get("conditions", [])
        if status_snapshot(status) != before:
            fresh = client.get(APPLICATION_API_VERSION, APPLICATION_KIND,
                               ns, name)
            fresh["status"] = status
            client.update_status(fresh)
        return Result()
