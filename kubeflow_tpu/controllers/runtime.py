"""Controller manager: watch → workqueue → reconcile.

The controller-runtime analog (the reference's controllers are kubebuilder
reconcilers, e.g. notebook_controller.go:57-144 watch wiring + :163
Reconcile). Semantics kept:

- Level-triggered: reconcilers read desired state from the store, never from
  the event (events only enqueue keys).
- One reconcile at a time per controller (single-reconciler concurrency
  model the reference relies on, SURVEY.md §5 race-detection note).
- Dedup: a key already queued is not queued twice.
- Requeue-on-error with bounded retries.
- Owned-object mapping: events on owned kinds enqueue the owner key
  (the Owns()/Watches() analog).

Deterministic test drive: `run_pending()` drains the queue synchronously.
Production drive: `start()` spins a daemon thread per controller.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..api import k8s
from ..cluster.client import KubeClient, Watch
from ..obs import controlplane as ctrlobs
from ..obs import registry as obsreg

log = logging.getLogger(__name__)


def _reconcile_metrics(controller: str) -> tuple:
    """(latency histogram child, error counter child, queue-depth gauge
    child, retries-exhausted counter child, workqueue-dwell histogram
    child) for one controller — the per-stage accounting every hosted
    reconciler gets for free from the manager loop. Resolved once per
    Controller and held (the registry's resolve-once hot-path rule)."""
    labels = ("controller",)
    return (
        obsreg.histogram(
            "kftpu_reconcile_seconds",
            "wall time of one reconcile pass",
            labels=labels).labels(controller=controller),
        obsreg.counter(
            "kftpu_reconcile_errors_total",
            "reconcile passes that raised (and were requeued)",
            labels=labels).labels(controller=controller),
        obsreg.gauge(
            "kftpu_workqueue_depth",
            "keys waiting in the controller workqueue",
            labels=labels).labels(controller=controller),
        obsreg.counter(
            "kftpu_reconcile_retries_exhausted_total",
            "keys given up on after max_retries failed reconciles "
            "(invisible to alerting as a log line; the blind resync is "
            "the only later recovery)",
            labels=labels).labels(controller=controller),
        ctrlobs.workqueue_dwell_histogram(controller),
    )


def ensure_trace_id(client: KubeClient, manifest: dict) -> dict:
    """Mint a job's trace id on first control-plane contact and persist
    it as the observability.kubeflow.org/trace-id annotation
    (obs/trace.py). Idempotent: once written by ANY component —
    scheduler pass or operator reconcile, whichever touches the job
    first — everyone else reads. Shared here so the two sides of the
    contract cannot drift (the binding_of pattern)."""
    from ..cluster.client import NotFoundError
    from ..obs.trace import TRACE_ID_ANNOTATION, mint_trace_id
    if k8s.annotations_of(manifest).get(TRACE_ID_ANNOTATION):
        return manifest
    # uid-derived: concurrent minters agree without coordination
    # uid + identity: concurrent minters still agree (both read the same
    # manifest), while jobs whose uids collide across clusters sharing
    # one span sink (FakeCluster soaks both hand out uid-1; a restored
    # etcd could too) never merge their streams in the goodput ledger
    meta = manifest.get("metadata", {})
    tid = mint_trace_id(f"{meta.get('uid', '')}:"
                        f"{k8s.namespace_of(manifest, 'default')}/"
                        f"{k8s.name_of(manifest)}")
    try:
        return client.patch(*k8s.key_of(manifest), {
            "metadata": {"annotations": {TRACE_ID_ANNOTATION: tid}}})
    except NotFoundError:
        return manifest


def trace_job_event(component: str, manifest: dict, name: str,
                    **attrs) -> None:
    """Append a point event to a job's trace from a control-plane
    component (no-op without a span sink — KFTPU_SPAN_PATH unset — or
    before the job has a trace id)."""
    from ..obs.trace import TRACE_ID_ANNOTATION, default_tracer
    tracer = default_tracer(component)
    if tracer is None:
        return
    tid = k8s.annotations_of(manifest).get(TRACE_ID_ANNOTATION)
    if not tid:
        return
    tracer.event(name, trace_id=tid,
                 job=f"{k8s.namespace_of(manifest, 'default')}/"
                     f"{k8s.name_of(manifest)}", **attrs)

# A reconcile key: (namespace, name) of the primary object.
Key = tuple[str, str]


def status_snapshot(status: dict) -> str:
    """Stable serialization of a status dict, for write-on-change guards.

    Reconcilers that unconditionally update_status retrigger their own watch
    and reconcile forever; compare snapshots taken before/after mutation and
    skip the write when equal.
    """
    return k8s.snapshot(status)


@dataclass
class Result:
    requeue: bool = False
    requeue_after: float = 0.0


class Reconciler:
    """Interface: reconcile one object identified by key, level-triggered."""

    #: (apiVersion, kind) of the primary resource
    primary: tuple[str, str] = ("", "")
    #: (apiVersion, kind) list of owned resources whose events map to owners
    owns: list[tuple[str, str]] = []

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        raise NotImplementedError

    def map_event(self, client: KubeClient, obj: dict) -> list[Key]:
        """Optional extra event→keys mapping for watched objects that do
        not carry an owner reference to the primary (label-selector
        aggregation, the controller-runtime EnqueueRequestsFromMapFunc
        analog). Called when owner-ref mapping yields nothing."""
        return []


class _WorkQueue:
    def __init__(self):
        self._items: list[Key] = []
        self._set: set[Key] = set()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # dwell accounting: first-enqueue time per queued key (a re-add
        # while queued dedups, so dwell measures from the FIRST add —
        # the latency the owner object actually experienced)
        self._added: dict[Key, float] = {}
        #: enqueue→pop dwell of the most recently popped key
        self.last_dwell_s: float = 0.0

    def add(self, key: Key) -> None:
        with self._cv:
            if key not in self._set:
                self._set.add(key)
                self._items.append(key)
                self._added[key] = time.monotonic()
                self._cv.notify()

    def pop(self, timeout: Optional[float] = None) -> Optional[Key]:
        with self._cv:
            if not self._items and timeout:
                self._cv.wait(timeout)
            if not self._items:
                return None
            key = self._items.pop(0)
            self._set.discard(key)
            self.last_dwell_s = \
                time.monotonic() - self._added.pop(key, time.monotonic())
            return key

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


@dataclass
class Controller:
    reconciler: Reconciler
    client: KubeClient
    max_retries: int = 5
    # Error-requeue pacing: a failing reconcile re-enters the queue after
    # a jittered exponential delay (base * 2^(attempt-1), capped) instead
    # of immediately — a persistently failing key must not hot-loop
    # through its whole retry budget in microseconds, hammering the
    # apiserver with the same doomed writes. Jitter is seeded by
    # (key, attempt) so retries are deterministic under test.
    retry_backoff_s: float = 0.05
    retry_backoff_max_s: float = 5.0
    # Leader election (cluster/lease.py LeaderElector): when set, this
    # controller processes keys ONLY while its elector holds the lease.
    # Events keep pumping either way (a hot standby watches but does not
    # write); on gaining leadership the full relist re-enqueues so the
    # new leader adopts whatever happened while it was a follower.
    elector: Optional[object] = None
    # Periodic full relist → enqueue (controller-runtime SyncPeriod
    # analog). A watch event lost in flight (stream drop, chaos-injected
    # fault, apiserver hiccup between reconnect and relist) would
    # otherwise never re-enqueue its key; the resync bounds that blind
    # spot. 0 = off (deterministic tests drive enqueue_existing
    # themselves).
    resync_interval: float = 0.0
    queue: _WorkQueue = field(default_factory=_WorkQueue)
    #: relist records ({reason, objects, time}) — initial sync, resync,
    #: leadership gain; the failover tests assert exactly-one here
    relists: list = field(default_factory=list)
    _watches: list[Watch] = field(default_factory=list)
    _retries: dict[Key, int] = field(default_factory=dict)
    _stop: threading.Event = field(default_factory=threading.Event)
    _delayed: list[tuple[float, Key]] = field(default_factory=list)
    _last_resync: float = 0.0
    # (latency, errors, depth) metric children — resolved on first use
    # and held for the controller's lifetime (hot-path rule)
    _metrics: Optional[tuple] = None

    def __post_init__(self):
        # the audit seam (obs/controlplane.py): every hosted reconciler
        # drives the cluster through an AuditingKubeClient labeled by
        # its controller identity, so per-pass write attribution and the
        # client-vs-server reconciliation work on ALL production paths.
        # Stacked wrappers (chaos, recording) audit what the component
        # ISSUED; an already-audited client is not double-wrapped.
        if not isinstance(self.client, ctrlobs.AuditingKubeClient):
            self.client = ctrlobs.AuditingKubeClient(self.client,
                                                     self._name())

    def _name(self) -> str:
        """The controller's metric/audit identity — the reconciler's
        declared controller_name, falling back to its primary kind
        (the same rule the reconcile metrics use)."""
        return (getattr(self.reconciler, "controller_name", None)
                or (self.reconciler.primary[1] or "unknown").lower())

    def _note_relist(self, reason: str, objects: int) -> None:
        self.relists.append({"reason": reason, "objects": objects,
                             "time": time.time()})
        ctrlobs.record_relist(self._name(), reason, objects)

    # -- wiring -------------------------------------------------------------

    def bind_watches(self) -> None:
        av, kind = self.reconciler.primary
        w = self.client.watch(av, kind)
        self._watches.append(w)
        for oav, okind in self.reconciler.owns:
            self._watches.append(self.client.watch(oav, okind))

    def enqueue_existing(self) -> int:
        """Initial list → enqueue (informer initial sync analog).
        Returns the number of objects listed — relist accounting at the
        call sites (initial/resync/leader-gain) records it."""
        av, kind = self.reconciler.primary
        objs = self.client.list(av, kind)
        for obj in objs:
            self.queue.add((k8s.namespace_of(obj, "default"), k8s.name_of(obj)))
        return len(objs)

    def _map_event_key(self, obj: dict) -> Optional[Key]:
        av_kind = (obj.get("apiVersion"), obj.get("kind"))
        if av_kind == self.reconciler.primary:
            return (k8s.namespace_of(obj, "default"), k8s.name_of(obj))
        # owned object: map to controller owner reference
        pav, pkind = self.reconciler.primary
        for ref in obj.get("metadata", {}).get("ownerReferences", []) or []:
            if ref.get("kind") == pkind and ref.get("apiVersion") == pav:
                return (k8s.namespace_of(obj, "default"), ref.get("name", ""))
        return None

    def pump_events(self, budget: int = 1000) -> int:
        """Drain watch queues into the workqueue (non-blocking)."""
        n = 0
        for w in self._watches:
            while n < budget:
                ev = w.get(timeout=0)
                if ev is None:
                    break
                key = self._map_event_key(ev.obj)
                if key:
                    # DELETED of primary still enqueues: reconcile observes
                    # absence and cleans up (level-triggered).
                    self.queue.add(key)
                    n += 1
                elif (ev.obj.get("apiVersion"), ev.obj.get("kind")) != \
                        self.reconciler.primary:
                    for mapped in self.reconciler.map_event(self.client,
                                                            ev.obj):
                        self.queue.add(mapped)
                        n += 1
        now = time.monotonic()
        due = [k for t, k in self._delayed if t <= now]
        self._delayed = [(t, k) for t, k in self._delayed if t > now]
        for k in due:
            self.queue.add(k)
        if self.resync_interval > 0 and \
                now - self._last_resync >= self.resync_interval:
            self._last_resync = now
            try:
                listed = self.enqueue_existing()
            except Exception as e:  # noqa: BLE001 — resync is best-effort
                log.warning("resync list failed: %s", e)
            else:
                self._note_relist(ctrlobs.RELIST_RESYNC, listed)
        return n

    # -- execution ----------------------------------------------------------

    _was_leader: bool = False

    def _leader_gate(self) -> bool:
        """True when this replica may reconcile (no elector = always).
        A leadership GAIN triggers a full relist: keys that changed
        while we were a follower may have been reconciled by the old
        leader mid-flight — the new leader re-reads everything and
        level-triggered reconciles converge it."""
        if self.elector is None:
            return True
        leading = self.elector.ensure()
        if leading and not self._was_leader:
            try:
                listed = self.enqueue_existing()
            except Exception as e:  # noqa: BLE001 — adopt is best-effort
                log.warning("leader-gain relist failed: %s", e)
            else:
                self._note_relist(ctrlobs.RELIST_LEADER_GAIN, listed)
        self._was_leader = leading
        return leading

    def process_one(self) -> bool:
        if not self._leader_gate():
            return False
        key = self.queue.pop()
        if key is None:
            return False
        if self._metrics is None:
            # label by the reconciler's IDENTITY, not its primary kind:
            # the SliceScheduler's primary is also TPUJob, and merging
            # its cluster-wide pass latencies into the operator's
            # per-job histogram would poison both
            self._metrics = _reconcile_metrics(self._name())
        latency, errors, depth, exhausted, dwell = self._metrics
        dwell.observe(self.queue.last_dwell_s)
        t0 = time.perf_counter()
        try:
            # pass-scoped audit: phase timings, per-key reconcile→write
            # attribution, no-op classification. Reentrant — a
            # reconciler opening its own ctrl_pass (the scheduler)
            # joins this context instead of double-counting.
            with ctrlobs.ctrl_pass(self._name(),
                                   key=f"{key[0]}/{key[1]}"):
                res = self.reconciler.reconcile(self.client, key)
            self._retries.pop(key, None)
            if res.requeue_after > 0:
                self._delayed.append((time.monotonic() + res.requeue_after, key))
            elif res.requeue:
                self.queue.add(key)
        except Exception as e:  # noqa: BLE001 - reconcile errors requeue
            errors.inc()
            n = self._retries.get(key, 0) + 1
            self._retries[key] = n
            if n <= self.max_retries:
                # jittered exponential backoff through the _delayed
                # mechanism: an immediate re-add would burn the whole
                # retry budget in one hot loop with zero time for the
                # fault (an apiserver blip, a half-written sibling
                # object) to clear
                delay = min(self.retry_backoff_s * (2 ** (n - 1)),
                            self.retry_backoff_max_s)
                delay *= random.Random(f"{key}:{n}").uniform(1.0, 1.5)
                log.warning("reconcile %s failed (retry %d/%d in "
                            "%.3fs): %s", key, n, self.max_retries,
                            delay, e)
                self._delayed.append((time.monotonic() + delay, key))
            else:
                exhausted.inc()
                log.error("reconcile %s gave up after %d retries: %s",
                          key, self.max_retries, e)
        finally:
            # a failed pass's latency is still latency — observe both arms
            latency.observe(time.perf_counter() - t0)
            depth.set(len(self.queue))
        return True

    def run_pending(self, max_iters: int = 1000) -> int:
        """Deterministic drain: pump events + process until quiescent.
        A follower (elector present, lease not held) pumps its watches
        and returns — watching without writing is exactly the hot
        standby's job."""
        done = 0
        for _ in range(max_iters):
            self.pump_events()
            if self.elector is not None and not self._leader_gate():
                break
            if not self.process_one():
                self.pump_events()
                if len(self.queue) == 0:
                    break
            else:
                done += 1
        return done

    def start(self, poll_interval: float = 0.05) -> threading.Thread:
        def loop():
            while not self._stop.is_set():
                self.pump_events()
                if not self.process_one():
                    time.sleep(poll_interval)
        t = threading.Thread(target=loop, daemon=True,
                             name=f"ctrl-{self.reconciler.primary[1]}")
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        for w in self._watches:
            w.close()  # detach from the server so events stop accumulating


class Manager:
    """Holds a set of controllers over one client (manager.Manager analog)."""

    def __init__(self, client: KubeClient):
        self.client = client
        self.controllers: list[Controller] = []

    def add(self, reconciler: Reconciler, **kwargs) -> Controller:
        c = Controller(reconciler=reconciler, client=self.client, **kwargs)
        c.bind_watches()
        c._note_relist(ctrlobs.RELIST_INITIAL, c.enqueue_existing())
        self.controllers.append(c)
        return c

    def run_pending(self, rounds: int = 10) -> None:
        """Drain all controllers to quiescence (test/deterministic mode).
        Multiple rounds because one controller's writes enqueue another's.
        Every controller must drain every round — any() would short-circuit
        at the first busy controller and starve the rest."""
        for _ in range(rounds):
            done = 0
            for c in self.controllers:
                done += c.run_pending()
            if not done:
                break

    def start_all(self) -> list[threading.Thread]:
        return [c.start() for c in self.controllers]

    def stop_all(self) -> None:
        for c in self.controllers:
            c.stop()
