"""PodDefault mutating admission: inject env/volumes/mounts into pods.

The reference's admission-webhook (components/admission-webhook/main.go:
filterPodDefaults :69, conflict checks :96-131, merge :278-316) is a
mutating webhook server; here the same logic is a pure function applied at
the apiserver admission point (cluster/fake.py admission hooks — the
in-memory analog of a MutatingWebhookConfiguration), so controllers and
tests exercise identical semantics.

PodDefault CR (poddefault_types.go): spec.selector (label selector),
spec.{env, envFrom, volumeMounts, volumes, annotations, labels,
serviceAccountName}.
"""

from __future__ import annotations

import logging

from ..api import k8s
from ..cluster.client import KubeClient

log = logging.getLogger(__name__)

PODDEFAULT_API_VERSION = "kubeflow.org/v1alpha1"
PODDEFAULT_KIND = "PodDefault"
APPLIED_ANNOTATION_PREFIX = "poddefault.admission.kubeflow.org/poddefault-"


class PodDefaultConflict(Exception):
    """Two selected PodDefaults disagree (same env/volume name, different
    value) — the reference rejects the pod rather than guess (main.go:96)."""


def select_pod_defaults(pod: dict, defaults: list[dict]) -> list[dict]:
    labels = k8s.labels_of(pod)
    out = []
    for pd in defaults:
        selector = k8s.selector_from(
            pd.get("spec", {}).get("selector"))
        # k8s LabelSelector convention: empty selector matches everything
        if all(labels.get(k) == v for k, v in selector.items()):
            out.append(pd)
    return sorted(out, key=k8s.name_of)


def check_conflicts(defaults: list[dict]) -> None:
    env_seen: dict[str, dict] = {}
    vol_seen: dict[str, dict] = {}
    mount_seen: dict[str, str] = {}
    for pd in defaults:
        spec = pd.get("spec", {})
        for e in spec.get("env", []) or []:
            # compare the FULL entry: two defaults injecting the same name
            # from different valueFrom sources conflict just as surely as
            # two literal values do
            name = e.get("name")
            if name in env_seen and env_seen[name] != e:
                raise PodDefaultConflict(
                    f"env {name}: {env_seen[name]!r} vs {e!r} "
                    f"(poddefault {k8s.name_of(pd)})")
            env_seen[name] = e
        for v in spec.get("volumes", []) or []:
            name = v.get("name")
            if name in vol_seen and vol_seen[name] != v:
                raise PodDefaultConflict(
                    f"volume {name} defined differently by multiple "
                    f"poddefaults (poddefault {k8s.name_of(pd)})")
            vol_seen[name] = v
        for m in spec.get("volumeMounts", []) or []:
            name, path = m.get("name"), m.get("mountPath")
            if name in mount_seen and mount_seen[name] != path:
                raise PodDefaultConflict(
                    f"volumeMount {name}: {mount_seen[name]!r} vs {path!r} "
                    f"(poddefault {k8s.name_of(pd)})")
            mount_seen[name] = path


def apply_pod_defaults(pod: dict, defaults: list[dict]) -> dict:
    """Merge selected PodDefaults into the pod (idempotent: existing names
    win, the reference's merge semantics main.go:278-316)."""
    if not defaults:
        return pod
    check_conflicts(defaults)
    spec = pod.setdefault("spec", {})
    containers = spec.get("containers", []) or []
    for pd in defaults:
        pspec = pd.get("spec", {})
        for v in pspec.get("volumes", []) or []:
            vols = spec.setdefault("volumes", [])
            if not any(x.get("name") == v.get("name") for x in vols):
                vols.append(dict(v))
        if pspec.get("serviceAccountName") and \
                not spec.get("serviceAccountName"):
            spec["serviceAccountName"] = pspec["serviceAccountName"]
        for c in containers:
            for e in pspec.get("env", []) or []:
                env = c.setdefault("env", [])
                if not any(x.get("name") == e.get("name") for x in env):
                    env.append(dict(e))
            for ef in pspec.get("envFrom", []) or []:
                envfrom = c.setdefault("envFrom", [])
                if ef not in envfrom:
                    envfrom.append(dict(ef))
            for m in pspec.get("volumeMounts", []) or []:
                mounts = c.setdefault("volumeMounts", [])
                if not any(x.get("name") == m.get("name") for x in mounts):
                    mounts.append(dict(m))
        meta = pod.setdefault("metadata", {})
        anns = meta.setdefault("annotations", {})
        for ak, av in (pspec.get("annotations") or {}).items():
            anns.setdefault(ak, av)
        labels = meta.setdefault("labels", {})
        for lk, lv in (pspec.get("labels") or {}).items():
            labels.setdefault(lk, lv)
        anns[APPLIED_ANNOTATION_PREFIX + k8s.name_of(pd)] = \
            pd.get("metadata", {}).get("resourceVersion", "0")
    return pod


class PodDefaultsWebhook:
    """Admission hook: install with
    ``cluster.admission_hooks.append(PodDefaultsWebhook(cluster))``.

    On conflict the pod is admitted UNMUTATED with a warning — matching the
    reference webhook's failurePolicy choice of not blocking pod creation.
    """

    def __init__(self, client: KubeClient):
        self.client = client

    def __call__(self, obj: dict) -> dict:
        if obj.get("kind") != "Pod":
            return obj
        ns = k8s.namespace_of(obj, "default")
        defaults = self.client.list(PODDEFAULT_API_VERSION, PODDEFAULT_KIND,
                                    ns)
        selected = select_pod_defaults(obj, defaults)
        if not selected:
            return obj
        try:
            return apply_pod_defaults(obj, selected)
        except PodDefaultConflict as e:
            log.warning("poddefault conflict for pod %s/%s: %s — admitting "
                        "unmutated", ns, k8s.name_of(obj), e)
            return obj
