"""Notebook controller: Notebook CR → StatefulSet + Service + VirtualService.

The reference's notebook-controller (components/notebook-controller/
pkg/controller/notebook/notebook_controller.go: watch wiring :57-144,
Reconcile :163, generateStatefulSet :313, generateService :367,
generateVirtualService :414). The CR spec wraps a full PodSpec in a
template (notebook_types.go:28-35 — SURVEY.md §2.6 "CR wraps PodSpec"),
and status is condition-based. A notebook requesting ``google.com/tpu``
schedules onto TPU hosts via the extended resource, so interactive
development on a single-host slice works the same way training pods do.
"""

from __future__ import annotations

import copy
import logging

from ..api import k8s
from ..cluster.client import KubeClient, NotFoundError
from .runtime import Key, Reconciler, Result, status_snapshot

log = logging.getLogger(__name__)

NOTEBOOK_API_VERSION = "kubeflow.org/v1alpha1"
NOTEBOOK_KIND = "Notebook"
NOTEBOOK_PORT = 8888
NOTEBOOK_NAME_LABEL = "notebook-name"
TPU_RESOURCE = "google.com/tpu"


class NotebookReconciler(Reconciler):
    primary = (NOTEBOOK_API_VERSION, NOTEBOOK_KIND)
    # pod state arrives transitively: pod events → STS reconciler updates
    # STS status → STS MODIFIED maps here (pods carry only the STS owner
    # ref, so watching pods directly would never map to a Notebook key)
    owns = [("apps/v1", "StatefulSet"), ("v1", "Service"),
            ("networking.istio.io/v1alpha3", "VirtualService")]

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            nb = client.get(NOTEBOOK_API_VERSION, NOTEBOOK_KIND, ns, name)
        except NotFoundError:
            return Result()  # cascade GC reaps children

        client.apply(self._statefulset(nb))
        client.apply(self._service(nb))
        client.apply(self._virtual_service(nb))

        # condition-based status from the notebook pod, the reference's
        # containerState mirroring (notebook_controller.go pod watch)
        pod = client.get_or_none("v1", "Pod", ns, f"{name}-0")
        phase = (pod or {}).get("status", {}).get("phase", "Waiting")
        status = dict(nb.get("status", {}))
        before = status_snapshot(status)
        status["readyReplicas"] = 1 if phase == "Running" else 0
        status["containerState"] = {"Running": {"running": {}},
                                    "Pending": {"waiting": {}},
                                    "Failed": {"terminated": {}}}.get(
                                        phase, {"waiting": {}})
        if status_snapshot(status) != before:
            fresh = client.get(NOTEBOOK_API_VERSION, NOTEBOOK_KIND, ns, name)
            fresh["status"] = status
            k8s.set_condition(
                fresh, k8s.Condition("Ready",
                                     "True" if phase == "Running" else "False",
                                     phase, f"notebook pod is {phase}"))
            client.update_status(fresh)
        return Result()

    # -- children ------------------------------------------------------------

    def _statefulset(self, nb: dict) -> dict:
        ns, name = k8s.namespace_of(nb, "default"), k8s.name_of(nb)
        template = copy.deepcopy(
            nb.get("spec", {}).get("template", {}) or {})
        pod_spec = template.setdefault("spec", {})
        pod_spec.setdefault("securityContext", {"fsGroup": 100})
        # TPU placement: the google.com/tpu resource request drives
        # scheduling; hardcoding an accelerator nodeSelector here would pin
        # notebooks to one TPU generation and wedge them on other pools
        labels = template.setdefault("metadata", {}).setdefault("labels", {})
        labels.update({"app": name, NOTEBOOK_NAME_LABEL: name})
        sts = {
            "apiVersion": "apps/v1", "kind": "StatefulSet",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "replicas": 1,
                "serviceName": name,
                "selector": {"matchLabels": {NOTEBOOK_NAME_LABEL: name}},
                "template": template,
            },
        }
        k8s.set_owner(sts, nb)
        return sts

    def _service(self, nb: dict) -> dict:
        ns, name = k8s.namespace_of(nb, "default"), k8s.name_of(nb)
        svc = {
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "selector": {NOTEBOOK_NAME_LABEL: name},
                "ports": [{"name": "http", "port": 80,
                           "targetPort": NOTEBOOK_PORT}],
            },
        }
        k8s.set_owner(svc, nb)
        return svc

    def _virtual_service(self, nb: dict) -> dict:
        ns, name = k8s.namespace_of(nb, "default"), k8s.name_of(nb)
        prefix = f"/notebook/{ns}/{name}/"
        vs = {
            "apiVersion": "networking.istio.io/v1alpha3",
            "kind": "VirtualService",
            "metadata": {"name": f"notebook-{name}", "namespace": ns},
            "spec": {
                "gateways": ["kubeflow/kubeflow-gateway"],
                "hosts": ["*"],
                "http": [{
                    "match": [{"uri": {"prefix": prefix}}],
                    "rewrite": {"uri": "/"},
                    "route": [{"destination": {
                        "host": f"{name}.{ns}.svc.cluster.local",
                        "port": {"number": 80}}}],
                }],
            },
        }
        k8s.set_owner(vs, nb)
        return vs
