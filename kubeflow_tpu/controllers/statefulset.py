"""Minimal StatefulSet reconciler (the kube-controller-manager analog).

Real clusters run notebooks as StatefulSets (notebook-controller emits STS,
notebook_controller.go:313) and rely on the built-in statefulset controller
to create the pods. Our in-memory control plane (cluster/fake.py) models
only the apiserver + scheduler, so this reconciler supplies the built-in:
ordinal pods ``<sts>-0..replicas-1`` from the pod template, owner-ref'd for
cascade GC, status.readyReplicas from pod phases.
"""

from __future__ import annotations

import copy
import logging

from ..api import k8s
from ..cluster.client import KubeClient, NotFoundError
from .runtime import Key, Reconciler, Result, status_snapshot

log = logging.getLogger(__name__)

TEMPLATE_HASH_LABEL = "controller.kubernetes.io/pod-template-hash"


def _template_hash(template: dict) -> str:
    import hashlib
    return hashlib.sha1(k8s.snapshot(template).encode()).hexdigest()[:10]


class StatefulSetReconciler(Reconciler):
    primary = ("apps/v1", "StatefulSet")
    owns = [("v1", "Pod")]

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            sts = client.get("apps/v1", "StatefulSet", ns, name)
        except NotFoundError:
            return Result()
        spec = sts.get("spec", {})
        replicas = int(spec.get("replicas", 1))
        template = spec.get("template", {}) or {}
        selector = k8s.selector_from(spec.get("selector"))

        pods = [p for p in client.list("v1", "Pod", ns)
                if k8s.is_owned_by(p, sts)]
        by_name = {k8s.name_of(p): p for p in pods}
        thash = _template_hash(template)

        requeue = False
        for i in range(replicas):
            pod_name = f"{name}-{i}"
            existing = by_name.get(pod_name)
            if existing is not None:
                if k8s.labels_of(existing).get(TEMPLATE_HASH_LABEL) == thash:
                    continue
                # template changed: delete + recreate next pass (the STS
                # rolling-update analog) — silently keeping the stale pod
                # would make spec edits (e.g. a Notebook image change)
                # no-ops with a healthy-looking status
                client.delete("v1", "Pod", ns, pod_name)
                requeue = True
                continue
            pod = {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {
                    "name": pod_name, "namespace": ns,
                    "labels": {**(template.get("metadata", {})
                                  .get("labels") or {}), **selector,
                               "statefulset.kubernetes.io/pod-name": pod_name,
                               TEMPLATE_HASH_LABEL: thash},
                },
                "spec": copy.deepcopy(template.get("spec", {})),
            }
            k8s.set_owner(pod, sts)
            client.create(pod)
        # scale down: remove highest ordinals first (STS semantics)
        for pod_name in sorted(by_name):
            try:
                ordinal = int(pod_name.rsplit("-", 1)[1])
            except (IndexError, ValueError):
                continue
            if ordinal >= replicas:
                client.delete("v1", "Pod", ns, pod_name)

        ready = sum(1 for p in pods
                    if p.get("status", {}).get("phase") == "Running")
        status = dict(sts.get("status", {}))
        before = status_snapshot(status)
        status.update({"replicas": replicas, "readyReplicas": ready})
        if status_snapshot(status) != before:
            fresh = client.get("apps/v1", "StatefulSet", ns, name)
            fresh["status"] = status
            client.update_status(fresh)
        return Result(requeue=requeue)
