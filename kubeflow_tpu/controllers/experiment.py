"""Experiment reconciler: hyperparameter-search trials as TPUJob gangs.

The reference's studyjob-controller loop (SURVEY.md §3.5) rebuilt on the
Experiment API (api/experiment.py): ask the in-process suggestion engine
for assignments, stamp them into ``spec.trialTemplate``, and keep up to
``spec.parallelism`` trials in flight as ordinary TPUJobs — every trial
is a gang-scheduled slice riding the same queue, quota, and FIFO as any
production job (the scheduler never learns trials exist).

What makes a trial swarm cheap here (ISSUE 19):

- **Warm starts.** Each trial's env sets ``KFTPU_RUNTIME_SCHEDULE=1``:
  the worker feeds tuned scalars (lr/warmup/total steps) to the
  optimizer as runtime state and keys the AOT/compile cache on
  ``compile_shape_fingerprint`` — trials differing only in tuned scalars
  share one executable, so every trial after the first skips XLA.
- **Median stopping.** The worker emits one ``SPAN_OBJECTIVE`` event per
  drained metrics window; the reconciler reads the per-window series
  from the span sink and deletes a running trial whose objective falls
  below the median of its peers at the same window — the saved
  chip-hours are ledgered, not just implied.
- **Per-experiment ledger.** Completed trials' goodput ledgers
  (obs/goodput.py, chip-weighted like ``cluster_rollup``) roll into
  trials/hour, chip-hour goodput, warm-start fraction, and best
  objective — exported as the ``kftpu_experiment_*`` gauges.

PBT (``algorithm: pbt``) runs the population in generations of
``parallelism``: when a generation completes, the bottom ``truncation``
fraction is replaced by clones of top performers — exploit resumes from
the winner's checkpoint via ``spec.resumeFrom`` (the elastic-restore
machinery reshapes it onto the clone's slice), explore perturbs each
numeric parameter.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import time
from dataclasses import dataclass, field
from statistics import median
from typing import Any, Optional

from ..api import k8s
from ..api.experiment import (EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                              EXPERIMENT_LABEL, OBSERVATION_ANNOTATION,
                              SPAN_OBJECTIVE, T_FAILED, T_PENDING,
                              T_RUNNING, T_STOPPED, T_SUCCEEDED,
                              TRIAL_LABEL, Experiment)
from ..api.trainingjob import (COND_FAILED, COND_RUNNING, COND_SUCCEEDED,
                               KF_API_VERSION_V1ALPHA1,
                               KF_API_VERSION_V1BETA2, TPU_API_VERSION,
                               TrainingJob)
from ..cluster.client import KubeClient, NotFoundError
from ..obs import registry as obsreg
from ..obs.trace import TRACE_ID_ANNOTATION
from .runtime import (Key, Reconciler, Result, ensure_trace_id,
                      status_snapshot)

log = logging.getLogger(__name__)

#: env the reconciler injects into every trial container (beside
#: KFTPU_RUNTIME_SCHEDULE=1): which experiment/trial the worker belongs
#: to, for log lines and custom reporters.
EXPERIMENT_ENV = "KFTPU_EXPERIMENT"
TRIAL_NAME_ENV = "KFTPU_TRIAL"

_JOB_API = {"TPUJob": TPU_API_VERSION, "TFJob": KF_API_VERSION_V1BETA2,
            "PyTorchJob": KF_API_VERSION_V1BETA2,
            "MPIJob": KF_API_VERSION_V1ALPHA1}

_TERMINAL = (T_SUCCEEDED, T_FAILED, T_STOPPED)


def _inject_env(manifest: dict, env: dict[str, str]) -> None:
    """Append env vars to every container list in the manifest (the
    template's shape varies by job kind, so walk generically); values
    already present win — the template author knows better."""
    def walk(node):
        if isinstance(node, dict):
            containers = node.get("containers")
            if isinstance(containers, list):
                for c in containers:
                    if isinstance(c, dict):
                        ce = c.setdefault("env", [])
                        present = {e.get("name") for e in ce}
                        for name, value in env.items():
                            if name not in present:
                                ce.append({"name": name, "value": value})
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)
    walk(manifest)


def _inject_args(manifest: dict, assignments: dict[str, Any]) -> None:
    """Append ``--name=value`` pairs to the first container's args — the
    katib workerTemplate idiom (parameter names are literal CLI flags)."""
    def first_containers(node):
        if isinstance(node, dict):
            containers = node.get("containers")
            if isinstance(containers, list) and containers:
                return containers
            for v in node.values():
                found = first_containers(v)
                if found:
                    return found
        elif isinstance(node, list):
            for v in node:
                found = first_containers(v)
                if found:
                    return found
        return None

    containers = first_containers(manifest) or []
    for c in containers:
        args = c.setdefault("args", [])
        for name, value in assignments.items():
            flag = name if name.startswith("-") else f"--{name}"
            args.append(f"{flag}={value}")


@dataclass
class _ExpState:
    """In-memory per-experiment state (the suggestion engine is
    stateful). Rebuilt from status on controller restart — the status
    trial list is the durable record."""
    engine: Any
    next_index: int = 0
    params: dict = field(default_factory=dict)  # trial -> assignment
    collect_retries: dict = field(default_factory=dict)
    rng: Any = None  # PBT perturbation randomness


def _experiment_gauges():
    """The kftpu_experiment_* scrape surface (docs/operations.md metric
    catalog). Resolved lazily per call — the registry dedupes."""
    g = obsreg.gauge
    return {
        "trials": g("kftpu_experiment_trials",
                    "trial count per phase for one experiment",
                    labels=("namespace", "name", "phase")),
        "best": g("kftpu_experiment_best_objective",
                  "best objective value observed across the "
                  "experiment's completed trials",
                  labels=("namespace", "name")),
        "tph": g("kftpu_experiment_trials_per_hour",
                 "completed trials per wall-clock hour since the "
                 "experiment started", labels=("namespace", "name")),
        "chip_hours": g("kftpu_experiment_chip_hours",
                        "chip-hours by disposition: goodput/badput from "
                        "trial ledgers, saved = early-stop avoided",
                        labels=("namespace", "name", "category")),
        "warm": g("kftpu_experiment_warm_start_fraction",
                  "fraction of finished trials after the first that "
                  "started from a shared cached/AOT executable",
                  labels=("namespace", "name")),
    }


class ExperimentReconciler(Reconciler):
    primary = (EXPERIMENT_API_VERSION, EXPERIMENT_KIND)
    owns = [(TPU_API_VERSION, "TPUJob"), (KF_API_VERSION_V1BETA2, "TFJob"),
            (KF_API_VERSION_V1BETA2, "PyTorchJob"),
            (KF_API_VERSION_V1ALPHA1, "MPIJob")]

    #: reconciles to wait for a finished trial's metrics before
    #: declaring them unavailable (in-flight span drain / reporter lag)
    max_collect_retries = 5
    #: poll interval while a median stopping policy watches running trials
    stopping_poll_s = 1.0

    def __init__(self, seed: int = 0, span_path: Optional[str] = None):
        self.seed = seed
        self._span_path = span_path
        self._states: dict[str, _ExpState] = {}

    # -- plumbing ------------------------------------------------------------

    @property
    def span_path(self) -> Optional[str]:
        if self._span_path:
            return self._span_path
        from ..obs.trace import SPAN_PATH_ENV
        return os.environ.get(SPAN_PATH_ENV)

    def _state(self, exp: Experiment, manifest: dict) -> _ExpState:
        eid = manifest.get("metadata", {}).get("uid") or exp.name
        if eid in self._states:
            return self._states[eid]
        import random as _random
        state = _ExpState(engine=exp.make_engine(seed=self.seed),
                          rng=_random.Random(self.seed ^ hash(exp.name)))
        # restart recovery: replay the status trial list so the engine
        # (and the grid cursor) catch up to the previous process
        trials = manifest.get("status", {}).get("trials", []) or []
        if trials:
            state.next_index = len(trials)
            state.engine.suggest(len(trials))  # advance cursors
            for t in trials:
                state.params[t["name"]] = t.get("parameters", {})
                if t.get("status") in (T_SUCCEEDED, T_STOPPED) and \
                        t.get("objective") is not None:
                    state.engine.observe(t.get("parameters", {}),
                                         exp.sign * float(t["objective"]))
                elif t.get("status") == T_FAILED:
                    state.engine.observe_failure(t.get("parameters", {}))
        self._states[eid] = state
        return state

    # -- objective reads -----------------------------------------------------

    def _objective_series(self, trace_id: Optional[str],
                          metric: str) -> list[float]:
        """Per-window objective values for one trial from the span sink
        (runtime/worker.py SPAN_OBJECTIVE events), window-ordered."""
        path = self.span_path
        if not path or not trace_id or not os.path.exists(path):
            return []
        from ..obs.trace import load_spans
        try:
            spans = load_spans(path, trace_id=trace_id)
        except (OSError, ValueError):
            return []
        series: list[tuple[int, float]] = []
        for s in spans:
            if s.get("name") != SPAN_OBJECTIVE:
                continue
            a = s.get("attrs") or {}
            if metric not in a:
                continue
            try:
                series.append((int(a.get("window", len(series))),
                               float(a[metric])))
            except (TypeError, ValueError):
                continue
        series.sort(key=lambda wv: wv[0])
        return [v for _, v in series]

    def _collect_objective(self, client: KubeClient, ns: str,
                           trial: dict, job: dict,
                           metric: str) -> Optional[float]:
        """A finished trial's objective, in priority order: span-sink
        window series (last window) → observation annotation →
        ``<trial>-metrics`` ConfigMap. None = not reported (yet)."""
        series = self._objective_series(trial.get("traceId"), metric)
        if series:
            trial["windows"] = len(series)
            return series[-1]
        raw = k8s.annotations_of(job).get(OBSERVATION_ANNOTATION)
        if raw:
            try:
                obs = json.loads(raw)
                if isinstance(obs, dict) and metric in obs:
                    return float(obs[metric])
            except (TypeError, ValueError):
                pass
        cm = client.get_or_none("v1", "ConfigMap", ns,
                                f"{trial['name']}-metrics")
        if cm is not None:
            raw = (cm.get("data") or {}).get(metric)
            if raw is not None:
                try:
                    return float(raw)
                except (TypeError, ValueError):
                    pass
        return None

    def _trial_ledger(self, trial: dict) -> Optional[dict]:
        """The trial's goodput ledger from the span sink (None without
        a sink or trace). Works mid-flight too — wallSeconds grows as
        windows land — which is what the early-stop savings estimate
        reads."""
        path = self.span_path
        tid = trial.get("traceId")
        if not path or not tid or not os.path.exists(path):
            return None
        from ..obs.goodput import ledger_for
        try:
            ledger = ledger_for(path, tid)
        except (OSError, ValueError):
            return None
        return ledger if ledger.get("wallSeconds") else None

    @staticmethod
    def _start_kind(ledger: Optional[dict]) -> str:
        """warm/cold/aot verdict from the ledger's compile evidence."""
        if not ledger:
            return "unknown"
        kinds = ledger.get("compileByStartKind") or {}
        for k in ("aot", "warm"):
            if kinds.get(k):
                return k
        return "cold" if kinds.get("cold") else "unknown"

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            manifest = client.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                                  ns, name)
        except NotFoundError:
            return Result()  # owner refs cascade trial deletion

        if k8s.condition_true(manifest, COND_SUCCEEDED) or \
                k8s.condition_true(manifest, COND_FAILED):
            return Result()
        status = manifest.setdefault("status", {})
        status_before = status_snapshot(status)

        try:
            exp = Experiment.from_manifest(manifest)
        except ValueError as e:
            self._finish(client, manifest, COND_FAILED, "InvalidSpec",
                         str(e))
            return Result()
        state = self._state(exp, manifest)
        if not status.get("startedAt"):
            status["startedAt"] = round(time.time(), 3)

        trials: list[dict] = status.get("trials", []) or []
        metric = exp.objective_metric

        # 1. sync trial states from worker jobs; collect objectives
        pending_collect = False
        for t in trials:
            if t["status"] in _TERMINAL:
                continue
            job = client.get_or_none(_JOB_API[t["kind"]], t["kind"], ns,
                                     t["name"])
            if job is None:
                t["status"] = T_FAILED
                t["message"] = "trial job disappeared"
                state.engine.observe_failure(
                    state.params.get(t["name"], t.get("parameters", {})))
                continue
            if not t.get("traceId"):
                job = ensure_trace_id(client, job)
                tid = k8s.annotations_of(job).get(TRACE_ID_ANNOTATION)
                if tid:
                    t["traceId"] = tid
            if k8s.condition_true(job, COND_FAILED):
                t["status"] = T_FAILED
                state.engine.observe_failure(
                    state.params.get(t["name"], t.get("parameters", {})))
                self._seal_ledger(t)
            elif k8s.condition_true(job, COND_SUCCEEDED):
                done = self._settle_success(client, ns, t, job, exp, state)
                pending_collect = pending_collect or not done
            elif k8s.condition_true(job, COND_RUNNING):
                t["status"] = T_RUNNING

        # 2. median early stopping over the span-sink window series
        if exp.early_stopping is not None and \
                exp.early_stopping.policy == "median":
            self._median_stop(client, ns, trials, exp, state)

        # 3. spawn up to parallelism (PBT spawns via generations)
        n_failed = sum(1 for t in trials if t["status"] == T_FAILED)
        max_failed = exp.max_failed_trials if \
            exp.max_failed_trials is not None else exp.max_trials
        best = self._best_trial(trials, exp)
        budget_left = exp.max_trials - len(trials)
        goal_met = best is not None and \
            exp.goal_reached(best.get("objective"))
        created = 0
        if n_failed <= max_failed and budget_left > 0 and not goal_met \
                and not state.engine.exhausted():
            in_flight = sum(1 for t in trials
                            if t["status"] not in _TERMINAL)
            if exp.algorithm == "pbt":
                created = self._pbt_generation(
                    client, manifest, exp, state, trials, in_flight,
                    budget_left)
            else:
                want = min(exp.parallelism - in_flight, budget_left)
                for assignment in (state.engine.suggest(want)
                                   if want > 0 else []):
                    trials.append(self._spawn_trial(
                        client, manifest, exp, state, assignment))
                    created += 1

        # 4. roll up status + metrics
        best = self._best_trial(trials, exp)
        self._rollup(status, trials, best, exp)
        status["trials"] = trials

        # 5. completion
        n_failed = status["trialsFailed"]
        n_done = sum(1 for t in trials if t["status"] in _TERMINAL)
        if n_failed > max_failed:
            self._finish(client, manifest, COND_FAILED, "TrialsFailed",
                         f"{n_failed} trials failed (max {max_failed})",
                         status)
            return Result()
        exhausted = state.engine.exhausted() or \
            len(trials) >= exp.max_trials or goal_met
        if trials and n_done == len(trials) and created == 0 and \
                not pending_collect and exhausted:
            if status["trialsSucceeded"] + status["trialsStopped"] == 0:
                self._finish(client, manifest, COND_FAILED,
                             "NoSuccessfulTrial", "all trials failed",
                             status)
            else:
                msg = (f"best trial {best['name']} objective "
                       f"{best['objective']}" if best else "completed")
                if goal_met:
                    msg += " (objective goal reached)"
                self._finish(client, manifest, COND_SUCCEEDED,
                             "ExperimentCompleted", msg, status)
            return Result()

        if status_snapshot(status) != status_before:
            self._write_status(client, manifest, status)
        if not k8s.condition_true(manifest, COND_RUNNING) and trials:
            self._set_condition(client, manifest, COND_RUNNING,
                                "TrialsRunning", "trials in progress")
        if pending_collect:
            return Result(requeue_after=0.05)
        if exp.early_stopping is not None and \
                exp.early_stopping.policy == "median" and \
                any(t["status"] == T_RUNNING for t in trials):
            # running trials publish new objective windows out-of-band
            # (the span sink) — no watch event fires, so the median
            # policy has to poll
            return Result(requeue_after=self.stopping_poll_s)
        return Result()

    # -- trial lifecycle -----------------------------------------------------

    def _settle_success(self, client: KubeClient, ns: str, trial: dict,
                        job: dict, exp: Experiment,
                        state: _ExpState) -> bool:
        """Terminal collection for a succeeded trial; False = metrics
        may still be in flight, requeue."""
        value = self._collect_objective(client, ns, trial, job,
                                        exp.objective_metric)
        if value is None:
            n = state.collect_retries.get(trial["name"], 0) + 1
            state.collect_retries[trial["name"]] = n
            if n < self.max_collect_retries:
                return False
            trial["status"] = T_FAILED
            trial["message"] = "objective metrics unavailable"
            state.engine.observe_failure(
                state.params.get(trial["name"],
                                 trial.get("parameters", {})))
            return True
        trial["status"] = T_SUCCEEDED
        trial["objective"] = value
        state.engine.observe(
            state.params.get(trial["name"], trial.get("parameters", {})),
            exp.sign * value)
        self._seal_ledger(trial)
        return True

    def _seal_ledger(self, trial: dict) -> None:
        """Fold the trial's final span-sink ledger into its record."""
        ledger = self._trial_ledger(trial)
        if ledger:
            trial["wallSeconds"] = ledger["wallSeconds"]
            trial["goodputSeconds"] = ledger["goodputSeconds"]
            chips = trial.get("chips") or ledger.get("chips") or 0
            trial["chipSeconds"] = round(
                chips * ledger["wallSeconds"], 3)
        trial["startKind"] = self._start_kind(ledger)

    def _median_stop(self, client: KubeClient, ns: str,
                     trials: list[dict], exp: Experiment,
                     state: _ExpState) -> None:
        """Median-stopping rule over aligned window indices: a running
        trial whose sign-normalized objective at its latest window is
        below the median of every OTHER reporting trial's value at that
        same window index gets deleted, its best-so-far standing as its
        result and its remaining chip-time ledgered as saved."""
        es = exp.early_stopping
        series_by_trial = {
            t["name"]: self._objective_series(t.get("traceId"),
                                              exp.objective_metric)
            for t in trials}
        reporting = {n: s for n, s in series_by_trial.items() if s}
        if len(reporting) < es.min_trials:
            return
        done_walls = [t["wallSeconds"] for t in trials
                      if t["status"] == T_SUCCEEDED
                      and t.get("wallSeconds")]
        for t in trials:
            if t["status"] != T_RUNNING:
                continue
            series = series_by_trial.get(t["name"]) or []
            w = len(series) - 1
            if w + 1 < es.start_window:
                continue
            peers = [s[min(w, len(s) - 1)] for n, s in reporting.items()
                     if n != t["name"]]
            if len(peers) < es.min_trials:
                continue
            mine = exp.sign * series[w]
            if mine >= exp.sign * median(peers):
                continue
            # stop: best-so-far is the trial's result (sign-normalized
            # best, reported in raw metric units)
            best_raw = max(series, key=lambda v: exp.sign * v)
            try:
                client.delete(_JOB_API[t["kind"]], t["kind"], ns,
                              t["name"])
            except NotFoundError:
                pass
            t["status"] = T_STOPPED
            t["stoppedEarly"] = True
            t["objective"] = best_raw
            t["message"] = (f"median-stopped at window {w + 1}: "
                            f"{series[w]:.6g} vs peer median")
            state.engine.observe(
                state.params.get(t["name"], t.get("parameters", {})),
                exp.sign * best_raw)
            self._seal_ledger(t)
            # chip-hours saved: expected full-trial wall (mean of
            # completed peers) minus what this trial already spent
            spent = t.get("wallSeconds", 0.0)
            chips = t.get("chips", 0)
            if done_walls and chips:
                expected = sum(done_walls) / len(done_walls)
                t["chipSecondsSaved"] = round(
                    max(0.0, (expected - spent)) * chips, 3)
            log.info("experiment %s/%s stopped trial %s early (%s)",
                     ns, exp.name, t["name"], t["message"])

    def _pbt_generation(self, client: KubeClient, manifest: dict,
                        exp: Experiment, state: _ExpState,
                        trials: list[dict], in_flight: int,
                        budget_left: int) -> int:
        """Generation step: gen 0 samples the population; each later
        generation starts only when the previous one has fully drained,
        replacing the bottom ``truncation`` fraction with perturbed
        clones resuming from winners' checkpoints."""
        pop = exp.parallelism
        if not trials:
            created = 0
            for assignment in state.engine.suggest(
                    min(pop, budget_left)):
                trials.append(self._spawn_trial(
                    client, manifest, exp, state, assignment,
                    generation=0))
                created += 1
            return created
        if in_flight > 0:
            return 0  # generation still draining
        gen = max(t.get("generation", 0) for t in trials)
        cohort = [t for t in trials if t.get("generation", 0) == gen]
        ranked = sorted(
            (t for t in cohort if t["status"] in (T_SUCCEEDED, T_STOPPED)
             and t.get("objective") is not None),
            key=lambda t: exp.sign * t["objective"], reverse=True)
        if not ranked:
            return 0  # whole generation failed; completion path decides
        n_replace = max(1, int(exp.pbt.truncation * len(ranked))) \
            if exp.pbt else 1
        created = 0
        for i, t in enumerate(ranked):
            if created >= budget_left:
                break
            if i >= len(ranked) - n_replace:
                # exploit+explore: clone a top performer, perturb params
                winner = ranked[i % max(1, len(ranked) - n_replace)]
                params = self._perturb(exp, state,
                                       winner.get("parameters", {}))
                parent = winner
            else:
                params = dict(t.get("parameters", {}))
                parent = t
            trials.append(self._spawn_trial(
                client, manifest, exp, state, params,
                generation=gen + 1,
                resume_from=parent.get("checkpointDir") or None,
                parent=parent["name"]))
            created += 1
        return created

    def _perturb(self, exp: Experiment, state: _ExpState,
                 params: dict) -> dict:
        out = dict(params)
        for p in exp.parameters:
            if p.name not in out:
                continue
            if p.type in ("double", "int"):
                factor = state.rng.choice(exp.pbt.perturb_factors) \
                    if exp.pbt else 1.2
                v = float(out[p.name]) * factor
                v = min(max(v, float(p.min)), float(p.max))
                out[p.name] = int(round(v)) if p.type == "int" else v
            else:
                out[p.name] = state.rng.choice(p.values)
        return out

    def _spawn_trial(self, client: KubeClient, manifest: dict,
                     exp: Experiment, state: _ExpState,
                     assignment: dict[str, Any], generation: int = 0,
                     resume_from: Optional[str] = None,
                     parent: Optional[str] = None) -> dict:
        ns = exp.namespace
        trial_name = f"{exp.name}-t{state.next_index}"
        state.next_index += 1
        state.params[trial_name] = dict(assignment)

        job = copy.deepcopy(exp.trial_template)
        kind = job.get("kind", "TPUJob")
        job.setdefault("apiVersion", _JOB_API[kind])
        meta = job.setdefault("metadata", {})
        meta["name"] = trial_name
        meta["namespace"] = meta.get("namespace") or ns
        labels = meta.setdefault("labels", {})
        labels[EXPERIMENT_LABEL] = exp.name
        labels[TRIAL_LABEL] = trial_name

        subs = {"trialName": trial_name, "experimentName": exp.name}
        for pname, v in assignment.items():
            subs[f"param.{pname.lstrip('-')}"] = v
        job = k8s.substitute_params(job, subs)
        if exp.inject_parameters:
            _inject_args(job, assignment)
        if resume_from:
            job.setdefault("spec", {})["resumeFrom"] = resume_from
        # the warm-start enabler: tuned scalars become runtime inputs so
        # this trial shares the namespace compile cache / AOT executable
        # with every sibling of the same compile shape
        _inject_env(job, {EXPERIMENT_ENV: exp.name,
                          TRIAL_NAME_ENV: trial_name,
                          "KFTPU_RUNTIME_SCHEDULE": "1"})
        k8s.set_owner(job, manifest)
        created = client.create(job)
        created = ensure_trace_id(client, created)

        trial = {"name": trial_name, "kind": kind, "status": T_PENDING,
                 "parameters": dict(assignment), "objective": None,
                 "generation": generation, "stoppedEarly": False,
                 "startKind": "unknown",
                 "chips": self._chips_of(job),
                 "checkpointDir": (job.get("spec") or {}).get(
                     "checkpointDir") or None}
        tid = k8s.annotations_of(created).get(TRACE_ID_ANNOTATION)
        if tid:
            trial["traceId"] = tid
        if parent:
            trial["parent"] = parent
        return trial

    @staticmethod
    def _chips_of(job: dict) -> int:
        try:
            tj = TrainingJob.from_manifest(job)
            tpu = tj.tpu_spec
            if tpu is not None and tpu.topology is not None:
                return tpu.topology.num_chips * tpu.num_slices
        except (ValueError, KeyError):
            pass
        return 0

    # -- rollup --------------------------------------------------------------

    def _best_trial(self, trials: list[dict],
                    exp: Experiment) -> Optional[dict]:
        best = None
        for t in trials:
            if t.get("objective") is None:
                continue
            if best is None or exp.better(t["objective"],
                                          best["objective"]):
                best = t
        return best

    def _rollup(self, status: dict, trials: list[dict],
                best: Optional[dict], exp: Experiment) -> None:
        n = {T_FAILED: 0, T_SUCCEEDED: 0, T_STOPPED: 0}
        for t in trials:
            if t["status"] in n:
                n[t["status"]] += 1
        done = sum(n.values())
        status["trialsTotal"] = len(trials)
        status["trialsRunning"] = len(trials) - done
        status["trialsSucceeded"] = n[T_SUCCEEDED]
        status["trialsFailed"] = n[T_FAILED]
        status["trialsStopped"] = n[T_STOPPED]
        if best is not None:
            status["bestTrial"] = {"name": best["name"],
                                   "parameters": best["parameters"],
                                   "objective": best["objective"]}
        elapsed_h = max(time.time() - float(status.get("startedAt")
                                            or time.time()), 1e-9) / 3600
        status["trialsPerHour"] = round(done / elapsed_h, 3)

        chip_s = sum(t.get("chipSeconds", 0.0) or 0.0 for t in trials)
        good_s = sum((t.get("goodputSeconds", 0.0) or 0.0)
                     * (t.get("chips", 0) or 0) for t in trials)
        saved_s = sum(t.get("chipSecondsSaved", 0.0) or 0.0
                      for t in trials)
        status["chipHours"] = {
            "total": round(chip_s / 3600, 6),
            "goodput": round(good_s / 3600, 6),
            "badput": round(max(chip_s - good_s, 0.0) / 3600, 6),
            "saved": round(saved_s / 3600, 6),
        }
        finished = [t for t in trials if t["status"] in _TERMINAL]
        known = [t for t in finished[1:]
                 if t.get("startKind") != "unknown"]
        warm = sum(1 for t in known
                   if t.get("startKind") in ("warm", "aot"))
        status["warmStartFraction"] = round(warm / len(known), 4) \
            if known else None

        g = _experiment_gauges()
        ns, name = exp.namespace, exp.name
        for phase, count in (("Running", status["trialsRunning"]),
                             ("Succeeded", n[T_SUCCEEDED]),
                             ("Failed", n[T_FAILED]),
                             ("Stopped", n[T_STOPPED])):
            g["trials"].labels(namespace=ns, name=name,
                               phase=phase).set(count)
        if best is not None:
            g["best"].labels(namespace=ns, name=name).set(
                best["objective"])
        g["tph"].labels(namespace=ns, name=name).set(
            status["trialsPerHour"])
        for cat, hours in status["chipHours"].items():
            g["chip_hours"].labels(namespace=ns, name=name,
                                   category=cat).set(hours)
        if status["warmStartFraction"] is not None:
            g["warm"].labels(namespace=ns, name=name).set(
                status["warmStartFraction"])

    # -- status plumbing -----------------------------------------------------

    def _write_status(self, client: KubeClient, manifest: dict,
                      status: dict) -> None:
        fresh = client.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                           k8s.namespace_of(manifest, "default"),
                           k8s.name_of(manifest))
        merged = dict(fresh.get("status", {}))
        merged.update({k: v for k, v in status.items()
                       if k != "conditions"})
        fresh["status"] = merged
        client.update_status(fresh)

    def _set_condition(self, client: KubeClient, manifest: dict,
                       ctype: str, reason: str, message: str) -> None:
        fresh = client.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                           k8s.namespace_of(manifest, "default"),
                           k8s.name_of(manifest))
        k8s.set_condition(fresh, k8s.Condition(ctype, "True", reason,
                                               message))
        client.update_status(fresh)

    def _finish(self, client: KubeClient, manifest: dict, ctype: str,
                reason: str, message: str,
                status: Optional[dict] = None) -> None:
        if status is not None:
            self._write_status(client, manifest, status)
        self._set_condition(client, manifest, ctype, reason, message)
        log.info("experiment %s/%s finished: %s (%s)",
                 k8s.namespace_of(manifest, "default"),
                 k8s.name_of(manifest), ctype, message)
