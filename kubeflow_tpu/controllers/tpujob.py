"""The training-job operator: TPUJob / TFJob / PyTorchJob / MPIJob reconciler.

The TPU-native replacement for tf-operator / pytorch-operator / mpi-operator
(deployed-by-reference images; CRDs + contracts in
kubeflow/tf-training/tf-job-operator.libsonnet, kubeflow/pytorch-job/,
kubeflow/mpi-job/). One reconciler serves all four kinds because the TPU
execution path is identical — only the legacy env contract differs per kind.

Semantics:

- **Gang scheduling (mandatory for TPU replicas).** All pods of a TPU replica
  carry a pod-group label + min-member annotation; the scheduler binds them
  all-or-nothing (the kube-batch PodGroup semantic the reference opts into
  via --enable-gang-scheduling, tf-job-operator.libsonnet:107-109,298-307).
  The slice is the atomic unit: the reconciler never creates a partial gang.
- **Topology contract.** Each TPU pod gets the jax.distributed bootstrap env
  (KFTPU_* — the TF_CONFIG analog, SURVEY.md §3.2) plus the TPU node
  selector and google.com/tpu resource request. Legacy replicas get their
  native contracts: TF_CONFIG (TFJob), MASTER_ADDR/RANK/WORLD_SIZE
  (PyTorchJob), hostlist env (MPIJob).
- **Slice-level failure domain.** Any failed pod in the gang restarts the
  WHOLE gang (delete + recreate) up to runPolicy.backoffLimit, then the job
  is Failed (SURVEY.md §5: "a dead worker kills the gang").
- **Success.** Process-0 ("chief") pod success completes the job — the
  tf-operator chief semantic; remaining pods are cleaned per cleanPodPolicy
  (the reason the reference's launcher.py:91-93 sleeps forever is exactly
  this policy; our workers exit and the policy reaps them).
- **Conditions.** Created/Running/Restarting/Succeeded/Failed, mirroring
  tf-operator's JobCondition vocabulary.
"""

from __future__ import annotations

import copy
import json
import logging
import math
import os
import random
import time

from ..api import k8s
from ..api.topology import TopologyContract, render_contracts
from ..api.trainingjob import (ANOMALY_ANNOTATION,
                               ANOMALY_COUNT_ANNOTATION,
                               ANOMALY_ROLLBACK_ANNOTATION, API_VERSIONS,
                               COND_CREATED, COND_FAILED, COND_QUEUED,
                               COND_RESTARTING, COND_RUNNING, COND_SUCCEEDED,
                               CLEAN_POD_ALL, CLEAN_POD_NONE,
                               CLEAN_POD_RUNNING, HEARTBEAT_ANNOTATION,
                               JOB_KINDS, POD_FAILED,
                               POD_RUNNING, POD_SUCCEEDED,
                               PREEMPTED_COUNT_ANNOTATION,
                               SCHED_REASON_ANNOTATION, SUSPECT_ANNOTATION,
                               ReplicaSpec, TrainingJob)
from ..cluster.client import (KubeClient, NotFoundError, apply_annotations,
                              update_with_conflict_retry)
from ..cluster.fake import POD_GROUP_LABEL, TPU_RESOURCE
from ..obs import registry as obsreg
from ..obs.trace import (SPAN_MAX_BYTES_ENV, SPAN_PATH_ENV,
                         TRACE_ID_ANNOTATION, TRACE_ID_ENV)
from ..scheduler import health, warmpool
from ..scheduler.inventory import POOL_LABEL, Placement, SliceRect
from .runtime import (Key, Reconciler, Result, ensure_trace_id,
                      trace_job_event)

log = logging.getLogger(__name__)

# condition precedence for the exported phase gauge (newest-wins, the
# dashboard's _job_phase walk plus Restarting)
_PHASE_ORDER = (COND_SUCCEEDED, COND_FAILED, COND_RESTARTING, COND_RUNNING,
                COND_QUEUED, COND_CREATED)


def _now() -> float:
    """Wall clock behind every timeout decision (backoff, stall, deadline,
    TTL) — one seam for tests/chaos to control time deterministically."""
    return time.time()


RESTART_COUNT_ANNOTATION = "kubeflow.org/gang-restart-count"
# unix time before which a failed gang must NOT be recreated (exponential
# backoff with jitter between gang restarts — restart-storm protection).
# Persisted as an annotation so a controller crash/restart cannot shortcut
# the wait the way an in-memory timer would.
RESTART_NOT_BEFORE_ANNOTATION = "kubeflow.org/gang-restart-not-before"
# gang shape at last creation (topology×slices per TPU replica): a changed
# fingerprint means the SPEC was resized/reshaped (deliberate restart on
# the new shape), not that members vanished — pod COUNT alone can't tell
# (equal-count reshapes exist: 2×2-host → 4×1-host, or 4x4 → 8x2)
GANG_SHAPE_ANNOTATION = "kubeflow.org/gang-shape"
REPLICA_TYPE_LABEL = "kubeflow.org/replica-type"
REPLICA_INDEX_LABEL = "kubeflow.org/replica-index"
DEFAULT_PORT = 2222
JAX_COORD_PORT = 8476


def _replica_pod_name(job: TrainingJob, rtype: str, index) -> str:
    return f"{job.name}-{rtype.lower()}-{index}"


def _tpu_pod_name(job: TrainingJob, slice_id: int, host_id: int) -> str:
    return f"{job.name}-worker-{slice_id}-{host_id}"


def _workers_service_name(job: TrainingJob) -> str:
    return f"{job.name}-workers"


class TrainingJobReconciler(Reconciler):
    """Reconciler for one job kind; instantiate once per kind."""

    def __init__(self, kind: str = "TPUJob"):
        self.kind = kind
        self.primary = (API_VERSIONS[kind], kind)
        self.owns = [("v1", "Pod"), ("v1", "Service")]
        # last exported phase per job key (the gang phase gauge clears a
        # job's previous-phase series instead of exporting two phases)
        self._exported_phase: dict[Key, str] = {}
        # Future-stamped heartbeats (worker clock ahead of ours): the
        # clamp state. (namespace, pod) -> (raw_beat, first_seen_at) —
        # staleness for a future beat is measured from when WE first saw
        # that value, so a skewed-but-hung worker still trips the stall
        # watchdog one timeout after we noticed it, instead of being
        # infinitely fresh until our clock catches its skew up.
        self._future_beats: dict[tuple, tuple] = {}
        # consecutive reconciles a worker trailed the chief's step by
        # >= health.STEP_SKEW_MIN_STEPS: (ns, job, pod) -> streak
        self._skew_streak: dict[tuple, int] = {}
        # heartbeat numeric-canary dedup: (ns, pod) -> last heartbeat
        # step already flagged for a non-finite lastLoss/lastGradNorm —
        # one health event per bad step, not one per reconcile tick
        self._numeric_flagged: dict[tuple, int] = {}

    # ------------------------------------------------------------ reconcile

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        namespace, name = key
        try:
            manifest = client.get(self.primary[0], self.kind, namespace, name)
        except NotFoundError:
            self._export_phase(key, None)
            # cascade GC removed the children with the owner — USUALLY.
            # The crash-consistency hole: a reconcile that read the job
            # just before its deletion creates pods just after the
            # cascade already ran (or a controller died mid-create and
            # its successor raced the delete) — orphans that pin TPU
            # chips forever, because nothing owns them anymore. The
            # orphan's own ADDED/MODIFIED event maps back to this key,
            # so level-triggered cleanup lands here.
            self._gc_orphans(client, namespace, name)
            return Result()
        manifest = ensure_trace_id(client, manifest)
        self._export_phase(key, manifest)
        job = TrainingJob.from_manifest(manifest)

        if k8s.condition_true(manifest, COND_SUCCEEDED) or \
                k8s.condition_true(manifest, COND_FAILED):
            return self._handle_finished(client, job, manifest)

        # Scheduler-managed jobs (spec.schedulingPolicy present) create
        # NOTHING until the slice scheduler writes the binding annotation:
        # admission is no longer placement. Unbound jobs sit in a visible
        # Queued condition; a binding REMOVED mid-run (preemption, or a
        # reshape invalidating it) tears the gang down through the
        # graceful path and re-queues — never a failure.
        binding = self._slice_binding(job, manifest)
        if job.scheduling_policy is not None and job.tpu_spec is not None \
                and binding is None:
            return self._handle_unbound(client, job, manifest)
        if binding is not None:
            # Elastic resize: the binding's shape IS the gang's shape.
            # A scheduler resize rewrites the binding to a different
            # topology inside the job's [minChips, maxChips] envelope;
            # adopting it here makes every downstream consumer — pod
            # entries, topology contracts, KFTPU_SHARDING, the gang
            # fingerprint — render the RESIZED gang, and the
            # fingerprint mismatch below restarts the old-shape gang
            # through the graceful GangResized path.
            job = self._job_at_binding_shape(job, binding)

        pods = client.list("v1", "Pod", namespace, selector=job.selector())
        by_name = {k8s.name_of(p): p for p in pods}

        self._ensure_services(client, job, manifest)

        phases = {k8s.name_of(p): p.get("status", {}).get("phase", "Pending")
                  for p in pods}
        chief = self._chief_pod_name(job)
        # chief success wins over concurrent worker failures AND vanishes:
        # a completed job must not be gang-restarted by a non-chief exiting
        # non-zero (or its pod object disappearing) during shutdown
        if phases.get(chief) == POD_SUCCEEDED:
            self._set_condition(client, manifest, COND_SUCCEEDED, "True",
                                "JobSucceeded", f"chief pod {chief} succeeded")
            self._cleanup_pods(client, job, pods)
            return Result()

        # activeDeadlineSeconds: a job running past its wall budget is
        # Failed (DeadlineExceeded) — measured from the Created condition's
        # transition time, which survives controller restarts
        deadline_in = self._deadline_remaining(job, manifest)
        if deadline_in is not None and deadline_in <= 0:
            self._set_condition(
                client, manifest, COND_FAILED, "True", "DeadlineExceeded",
                f"job exceeded activeDeadlineSeconds="
                f"{job.run_policy.active_deadline_seconds}")
            self._cleanup_pods(client, job, pods)
            return Result()

        # A TPU gang member VANISHING mid-run (node loss, preemption
        # deleting the pod object — no Failed phase ever appears) must
        # restart the WHOLE gang: the survivors' jax.distributed world
        # cannot re-admit a fresh peer, so recreating just the missing pod
        # would hang the slice forever. Scoped to TPU pods only — legacy
        # CPU replicas (TF PS/worker gRPC) reconnect to a solo recreation
        # the way the reference operators relied on. The Restarting
        # condition marks an intentional between-reconciles gap (we just
        # deleted the gang ourselves).
        tpu_entries = {rtype: self._tpu_pod_entries(job, rs)
                       for rtype, rs in job.replica_specs.items()
                       if rs.is_tpu}
        tpu_names = [n for entries in tpu_entries.values()
                     for n, _ in entries]
        shape = self._gang_shape(job, binding)
        shape_anno = k8s.annotations_of(manifest).get(GANG_SHAPE_ANNOTATION)
        if tpu_names and k8s.condition_true(manifest, COND_CREATED) \
                and not k8s.condition_true(manifest, COND_RESTARTING):
            if shape_anno is not None and \
                    self._shape_changed(shape_anno, shape):
                # spec RESIZE/RESHAPE (numSlices/topology changed), an
                # elastic scheduler resize (the adopted binding shape
                # changed), or a defrag migration (same shape, new
                # rects): the old shape/placement is baked into every
                # survivor's KFTPU_* env and node pinning, so the gang
                # restarts on the new one — deliberately, without
                # burning backoff budget (an operator action, not a
                # failure). No by_name guard: even with every pod already
                # gone this path must run so resumeFrom is set.
                return self._handle_gang_failure(
                    client, job, manifest, pods,
                    sorted(by_name) or ["<all>"],
                    reason="GangResized", count_restart=False)
            # a missing annotation (pre-annotation operator versions) must
            # still protect against the slice-hang: default to vanish
            # semantics, the safe restart
            missing = [n for n in tpu_names if n not in by_name]
            if missing:
                return self._handle_gang_failure(
                    client, job, manifest, pods, missing,
                    reason="GangPodsVanished")

        if k8s.condition_true(manifest, COND_RESTARTING):
            # restart backoff: the gang stays down until the persisted
            # not-before time passes (restart-storm protection) — requeue
            # for the remainder instead of recreating immediately
            wait = self._restart_backoff_remaining(manifest)
            if wait > 0:
                return Result(requeue_after=wait)

        created = self._ensure_pods(client, job, manifest, by_name,
                                    tpu_entries, binding=binding)
        if created:
            if tpu_names and shape_anno != shape:
                # conflict-safe: the scheduler writes bindings/state on
                # this same object concurrently — a stale-read update
                # here must re-read, not clobber (cluster/client.py)
                manifest = update_with_conflict_retry(
                    client, *k8s.key_of(manifest),
                    lambda obj: apply_annotations(
                        obj, {GANG_SHAPE_ANNOTATION: shape}))
            self._set_condition(client, manifest, COND_CREATED, "True",
                                "JobCreated", f"created {created} pods")
            if binding is not None:
                # the queue wait is over: the gang exists on its slices
                self._set_condition(client, manifest, COND_QUEUED, "False",
                                    "Bound",
                                    "slice binding present; gang created")
            # the intentional-gap marker is consumed: the gang exists again
            if k8s.condition_true(manifest, COND_RESTARTING):
                self._set_condition(client, manifest, COND_RESTARTING,
                                    "False", "GangRecreated",
                                    "gang pods recreated")
            return Result(requeue=True)

        failed = [n for n, ph in phases.items() if ph == POD_FAILED]
        if failed:
            # a failed pod carrying the sentinel's anomaly-evidence
            # annotation is NOT a crash: the worker tripped a numeric
            # detector and exited deliberately so the control plane can
            # roll the job back to its last-known-good checkpoint — a
            # separate budget, a rollback (not a plain restart), and SDC
            # evidence folded onto the suspect host
            evidence_pod, anomaly = self._anomaly_of(by_name, failed)
            if anomaly is not None:
                return self._handle_anomaly(
                    client, job, manifest, pods, failed, anomaly,
                    suspect=self._suspect_node(by_name, [evidence_pod]))
            return self._handle_gang_failure(
                client, job, manifest, pods, failed,
                suspect=self._suspect_node(by_name, failed),
                evidence=health.EVENT_POD_CRASH)

        # stall watchdog: a chief that is Running but has stopped advancing
        # its heartbeat is hung-not-dead (wedged collective, dead TPU
        # runtime under a live pod) — no Failed phase will ever appear, so
        # the watchdog is the only recovery path
        stalled = self._stalled_chief(job, manifest, by_name, chief)
        if stalled:
            return self._handle_gang_failure(
                client, job, manifest, pods, [chief], reason="StallTimeout",
                suspect=self._suspect_node(by_name, [chief]),
                evidence=health.EVENT_STALL)

        # per-worker stall: one wedged worker under a healthy chief (the
        # straggler-gone-dead case the chief-only watchdog misses) — the
        # fault is attributable to the stalled worker's host, so the
        # restart records it as the suspect and the scheduler migrates
        # the gang instead of restarting onto the same flaky host
        stalled_workers = self._stalled_workers(job, manifest, by_name,
                                                tpu_names, chief)
        if stalled_workers:
            return self._handle_gang_failure(
                client, job, manifest, pods, stalled_workers,
                reason="WorkerStallTimeout",
                suspect=self._suspect_node(by_name, stalled_workers),
                evidence=health.EVENT_WORKER_STALL)

        # straggler scoring (no teardown): per-worker step skew off the
        # heartbeat steps feeds the host health score
        if tpu_names:
            self._note_step_skew(job, by_name, tpu_names, chief, client)
            # numeric canary off the same heartbeats: a worker reporting
            # a non-finite lastLoss/lastGradNorm is flagged (host health
            # event + metric) even when the in-step sentinel is disabled
            self._note_numeric_health(job, by_name, tpu_names, client)
        # the rollback directive is consumed once the recreated gang
        # provably trained PAST the trip step: clear it so the NEXT
        # restart (whatever its cause) resumes from the newest
        # checkpoint again instead of the stale LKG pin
        self._clear_rollback_annotation(client, job, manifest, by_name,
                                        chief)

        running = sum(1 for ph in phases.values() if ph == POD_RUNNING)
        self._finalize_status(client, manifest, pods,
                              all_running=(running == job.total_pods()
                                           and running > 0))
        # timers that need a re-check without any cluster event: the
        # active deadline landing, and the next stall-watchdog probe
        requeue_in = [t for t in (deadline_in,) if t is not None and t > 0]
        if job.run_policy.stall_timeout_seconds:
            requeue_in.append(
                max(1.0, job.run_policy.stall_timeout_seconds / 2))
        return Result(requeue_after=min(requeue_in)) if requeue_in \
            else Result()

    # ------------------------------------------------------- observability

    def _export_phase(self, key: Key, manifest: dict | None) -> None:
        """The gang phase gauge: kftpu_job_phase{...,phase}=1 for the
        job's CURRENT phase only (the previous phase's series is
        removed; a deleted job exports nothing)."""
        g = obsreg.gauge(
            "kftpu_job_phase",
            "1 for the training job's current phase (condition walk)",
            labels=("namespace", "name", "kind", "phase"))
        namespace, name = key
        prev = self._exported_phase.get(key)
        phase = None
        if manifest is not None:
            phase = next((c for c in _PHASE_ORDER
                          if k8s.condition_true(manifest, c)), "Pending")
        if phase == prev:
            return
        if prev is not None:
            g.remove(namespace=namespace, name=name, kind=self.kind,
                     phase=prev)
        if phase in (None, COND_SUCCEEDED, COND_FAILED):
            # done or gone: the per-job watchdog/straggler state has
            # nothing left to watch — a long-lived controller must not
            # accumulate entries (or stale skew series) for every job
            # that ever stalled
            self._prune_job_state(namespace, name)
        if phase is None:
            # job object gone: its final-ledger series go with it (the
            # same rule as the phase gauge — a deleted job must not
            # export its decomposition forever)
            from ..obs.goodput import remove_job_ledger
            remove_job_ledger(namespace, name)
            self._exported_phase.pop(key, None)
            return
        g.labels(namespace=namespace, name=name, kind=self.kind,
                 phase=phase).set(1)
        self._exported_phase[key] = phase

    def _prune_job_state(self, namespace: str, name: str) -> None:
        """Drop the in-memory heartbeat-clamp and skew-streak entries
        for one job's pods (pod names are '<job>-<role>-...'), and its
        skew gauge series."""
        prefix = f"{name}-"
        self._future_beats = {
            k: v for k, v in self._future_beats.items()
            if not (k[0] == namespace and k[1].startswith(prefix))}
        self._numeric_flagged = {
            k: v for k, v in self._numeric_flagged.items()
            if not (k[0] == namespace and k[1].startswith(prefix))}
        self._skew_streak = {
            k: v for k, v in self._skew_streak.items()
            if not (k[0] == namespace and k[1] == name)}
        obsreg.gauge(
            "kftpu_job_step_skew",
            "chief step minus the slowest worker's heartbeat step",
            labels=("namespace", "name")).remove(
                namespace=namespace, name=name)

    def _trace_event(self, manifest: dict, name: str, **attrs) -> None:
        trace_job_event("operator", manifest, name, **attrs)

    # ---------------------------------------------------- slice scheduling

    @staticmethod
    def _slice_binding(job: TrainingJob,
                       manifest: dict) -> Placement | None:
        """The scheduler's placement for this job, or None when unbound.
        A binding whose shape no longer matches the spec (resize under
        it) reads as unbound: creating a gang on a stale placement would
        double-book chips the scheduler has already re-planned. Parse +
        shape check are the scheduler's own (scheduler/queue.py), so the
        two sides of the annotation contract cannot drift."""
        from ..scheduler.queue import binding_matches, binding_of
        placement = binding_of(manifest)
        if placement is None or not binding_matches(placement, job):
            return None
        return placement

    def _handle_unbound(self, client: KubeClient, job: TrainingJob,
                        manifest: dict) -> Result:
        """A scheduler-managed job without a binding: tear down whatever
        gang exists (preemption reclaim — the graceful delete path gives
        workers SIGTERM → forced checkpoint → exit 75) and surface a
        Queued condition. No backoff budget is burned: a preemption is a
        requeue, not a failure."""
        pods = client.list("v1", "Pod", job.namespace,
                           selector=job.selector())
        anns = k8s.annotations_of(manifest)
        preempted = int(anns.get(PREEMPTED_COUNT_ANNOTATION, "0")) > 0
        if pods:
            for p in pods:
                try:
                    client.delete("v1", "Pod",
                                  k8s.namespace_of(p, job.namespace),
                                  k8s.name_of(p))
                except NotFoundError:
                    pass
            if job.checkpoint_dir and not job.resume_from:
                # same resume loop as a gang restart: the re-bound gang
                # continues from the forced preemption checkpoint.
                # Conflict-safe RMW: the scheduler is rewriting this
                # object's annotations in the same window

                def _set_resume(obj: dict, ckpt=job.checkpoint_dir):
                    if obj.setdefault("spec", {}).get("resumeFrom"):
                        return None   # already set by a sibling path
                    obj["spec"]["resumeFrom"] = ckpt
                    return obj
                update_with_conflict_retry(client, *k8s.key_of(manifest),
                                           _set_resume)
            self._set_condition(client, manifest, COND_RUNNING, "False",
                                "Preempted" if preempted else "Unbound",
                                "gang torn down; awaiting re-bind")
        reason = "Preempted" if preempted else "AwaitingBinding"
        detail = anns.get(SCHED_REASON_ANNOTATION, "")
        self._set_condition(
            client, manifest, COND_QUEUED, "True", reason,
            detail or "waiting for the slice scheduler to bind this gang")
        return Result()

    # ------------------------------------------------------------- children

    def _ensure_services(self, client: KubeClient, job: TrainingJob,
                         manifest: dict) -> None:
        svc = k8s.make(
            "v1", "Service", _workers_service_name(job), job.namespace,
            labels=job.selector(),
            spec={
                "clusterIP": "None",  # headless: stable per-pod DNS
                "selector": job.selector(),
                "ports": [
                    {"name": "jax-coordinator", "port": JAX_COORD_PORT},
                    {"name": "legacy", "port": DEFAULT_PORT},
                ],
            },
        )
        k8s.set_owner(svc, manifest)
        if client.get_or_none(*k8s.key_of(svc)) is None:
            client.create(svc)

    @staticmethod
    def _tpu_pod_entries(job: TrainingJob, rs) -> list[tuple[str, object]]:
        """(pod name, topology contract) for every member of a TPU replica
        — the ONE place gang pod naming happens (_ensure_pods and the
        vanish detector both consume it; drift between them would make
        every pod look missing)."""
        contracts = render_contracts(
            job.name, job.namespace, rs.topology, rs.num_slices,
            port=JAX_COORD_PORT)
        return [(_tpu_pod_name(job, c.slice_id,
                               c.process_id % rs.topology.num_hosts), c)
                for c in contracts]

    @staticmethod
    def _job_at_binding_shape(job: TrainingJob,
                              binding: Placement) -> TrainingJob:
        """The job with its TPU replica spec swapped to the BINDING's
        shape (elastic resize: the scheduler may bind a shape other
        than the spec's nominal one, inside the minChips/maxChips
        envelope — _slice_binding already validated the envelope via
        binding_matches). Identity when the shapes agree."""
        import dataclasses

        from ..api.topology import parse_topology
        tpu = job.tpu_spec
        if tpu is None or tpu.topology is None:
            return job
        if binding.topology == tpu.topology.name \
                and binding.num_slices == tpu.num_slices:
            return job
        try:
            topo = parse_topology(binding.topology)
        except ValueError:
            return job
        specs = dict(job.replica_specs)
        specs["TPU"] = dataclasses.replace(
            tpu, topology=topo, num_slices=binding.num_slices)
        return dataclasses.replace(job, replica_specs=specs)

    @staticmethod
    def _shape_changed(shape_anno: str, shape: str) -> bool:
        """Whether the persisted fingerprint and the computed one name
        DIFFERENT gangs. A pre-placement-format annotation (no "@rects"
        suffix — written by an operator version before defrag
        migration existed) matches on the shape part alone: upgrading
        the operator must not read every healthy bound gang's
        annotation as a resize and restart the whole fleet at once.
        The annotation adopts the new format at the next real
        create/restart."""
        if shape_anno == shape:
            return False
        if "@" not in shape_anno and shape.split("@", 1)[0] == shape_anno:
            return False
        return True

    @staticmethod
    def _gang_shape(job: TrainingJob,
                    binding: Placement | None = None) -> str:
        """Shape fingerprint of the TPU replicas (topology×slices per
        replica type): the value behind GANG_SHAPE_ANNOTATION. With a
        binding, the PLACEMENT rides in the fingerprint too: a
        scheduler defrag migration moves the gang without changing its
        size, and the running pods (pinned to the old pool/rect) must
        still restart onto the new cells."""
        parts = [f"{rtype}:{rs.topology.name}x{rs.num_slices}"
                 for rtype, rs in sorted(job.replica_specs.items())
                 if rs.is_tpu and rs.topology is not None]
        shape = ";".join(parts)
        if binding is not None and binding.slices:
            rects = ",".join(
                f"{r.pool}:{r.x}.{r.y}.{r.h}x{r.w}"
                for r in binding.slices)
            shape += f"@{rects}"
        return shape

    def _ensure_pods(self, client: KubeClient, job: TrainingJob,
                     manifest: dict, existing: dict[str, dict],
                     tpu_entries: dict[str, list],
                     binding: Placement | None = None) -> int:
        # slice_id -> assigned rect (the scheduler's placement order IS
        # the slice order)
        slice_rects = {i: r for i, r in
                       enumerate(binding.slices)} if binding else {}
        # warm-pod adoption: the binding names the pre-initialized pods
        # this placement covers (scheduler stamps warmHosts at bind
        # time); retire them and mark the gang warm-started BEFORE the
        # cold-create below — rebinds, elastic resizes, and preemption
        # re-binds all come through here, which is exactly the point
        adopted = self._adopt_warm_pods(client, binding) \
            if binding is not None and binding.warm_hosts else []
        created = 0
        for rtype, rs in job.replica_specs.items():
            if rs.is_tpu:
                # all-or-nothing create: build every missing member first,
                # then emit the whole set (never a partial gang)
                gang_pods = [
                    self._build_tpu_pod(job, manifest, rs, c, pname,
                                        rect=slice_rects.get(c.slice_id))
                    for pname, c in tpu_entries[rtype]
                    if pname not in existing]
                for pod in gang_pods:
                    if adopted:
                        pod["metadata"]["annotations"][
                            warmpool.ADOPTED_ANNOTATION] = \
                            json.dumps(adopted)
                        self._add_env(pod,
                                      {warmpool.WARM_START_ENV: "1"})
                    client.create(pod)
                    created += 1
            else:
                for i in range(rs.replicas):
                    pname = _replica_pod_name(job, rtype, i)
                    if pname in existing:
                        continue
                    client.create(self._build_replica_pod(
                        job, manifest, rs, rtype, i, pname))
                    created += 1
        return created

    def _adopt_warm_pods(self, client: KubeClient,
                         binding: Placement) -> list[dict]:
        """Retire the warm pods the binding's warmHosts name; returns
        the slots whose pod actually existed (a slot whose pod is gone
        — raced away by another bind, or never created — degrades to a
        plain cold create for that host, never an error)."""
        adopted: list[dict] = []
        for slot in binding.warm_hosts:
            name = warmpool.warm_pod_name(slot["pool"], slot["host"])
            try:
                client.delete("v1", "Pod", warmpool.WARM_POOL_NAMESPACE,
                              name)
            except NotFoundError:
                continue
            adopted.append({"pool": slot["pool"],
                            "host": int(slot["host"])})
        if adopted:
            obsreg.counter(
                "kftpu_warm_pod_adoptions_total",
                "gang creations that adopted a pre-initialized warm "
                "pod instead of cold-creating").inc(len(adopted))
        return adopted

    def _base_pod(self, job: TrainingJob, manifest: dict, rs: ReplicaSpec,
                  name: str, rtype: str, index: str) -> dict:
        pod = copy.deepcopy(rs.template) or {}
        pod.setdefault("spec", {}).setdefault("containers",
                                              [{"name": "main", "image": "main"}])
        # operator-required labels LAST: a user template must not be able to
        # override the selector / replica identity labels
        labels = {**(pod.get("metadata", {}).get("labels") or {}),
                  **job.selector(), REPLICA_TYPE_LABEL: rtype.lower(),
                  REPLICA_INDEX_LABEL: str(index)}
        meta = {"name": name, "namespace": job.namespace, "labels": labels,
                "annotations": dict(pod.get("metadata", {}).get("annotations") or {})}
        pod = {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
               "spec": pod.get("spec", {})}
        pod["spec"].setdefault("restartPolicy", "Never")
        pod["spec"]["hostname"] = name
        pod["spec"]["subdomain"] = _workers_service_name(job)
        k8s.set_owner(pod, manifest)
        # checkpoint/resume contract on every replica kind: workers write to
        # checkpointDir and restore from resumeFrom before the loop
        # (runtime/worker.py); gang restart sets resumeFrom automatically
        # Pod self-identity (the downward-API analog): lets the worker
        # annotate its OWN pod with the liveness heartbeat the stall
        # watchdog reads (runtime/metrics.py HeartbeatReporter). The
        # operator forwards its own KFTPU_APISERVER so workers can build
        # an in-pod client for the heartbeat patch; without it the
        # reporter is a no-op (and the watchdog, seeing no heartbeat,
        # never trips — non-instrumented deployments keep working).
        env = {"KFTPU_POD_NAME": name, "KFTPU_POD_NAMESPACE": job.namespace}
        if os.environ.get("KFTPU_APISERVER"):
            env["KFTPU_APISERVER"] = os.environ["KFTPU_APISERVER"]
        # trace contract (obs/trace.py): the job's minted trace id rides
        # into every worker so its window spans stitch onto the control
        # plane's queued/bound/running events; the operator forwards its
        # own span sink so workers write where the control plane does,
        # unless the spec names one explicitly (obs_spec below wins)
        trace_id = k8s.annotations_of(manifest).get(TRACE_ID_ANNOTATION)
        if trace_id:
            env[TRACE_ID_ENV] = trace_id
        if os.environ.get(SPAN_PATH_ENV):
            env[SPAN_PATH_ENV] = os.environ[SPAN_PATH_ENV]
        if os.environ.get(SPAN_MAX_BYTES_ENV):
            # sink rotation cap rides along with the sink: workers
            # appending to the shared JSONL honor the same rotation
            # policy the control plane does (obs/trace.py)
            env[SPAN_MAX_BYTES_ENV] = os.environ[SPAN_MAX_BYTES_ENV]
        # spec.observability → KFTPU_SPAN_PATH / KFTPU_OBS_METRICS_PORT:
        # the worker's span sink and its own /metrics port
        env.update(job.obs_spec.to_env())
        if job.checkpoint_dir:
            env["KFTPU_CHECKPOINT_DIR"] = job.checkpoint_dir
        if job.resume_from:
            env["KFTPU_RESUME_FROM"] = job.resume_from
        if job.data_dir:
            env["KFTPU_DATA_DIR"] = job.data_dir
        if job.eval_data_dir:
            env["KFTPU_EVAL_DATA_DIR"] = job.eval_data_dir
        if job.tensorboard_dir:
            env["KFTPU_TB_DIR"] = job.tensorboard_dir
        if job.weight_update:
            # spec.weightUpdate → the worker's ZeRO-2 weight-update knob
            # (runtime/worker.py reads it into TrainStepBuilder)
            env["KFTPU_WEIGHT_UPDATE"] = job.weight_update
        if job.scheduling_policy is not None:
            # spec.schedulingPolicy → KFTPU_SCHED_{QUEUE,PRIORITY,
            # PREEMPTIBLE}: queue/priority are informational (logs,
            # metrics labels); preemptible tells the SIGTERM handler a
            # reclaim is a requeue, not a crash
            env.update(job.scheduling_policy.to_env())
        # spec.input → the overlapped-input-pipeline knobs: augment
        # worker processes (KFTPU_INPUT_WORKERS) and device prefetch
        # depth (KFTPU_DEVICE_PREFETCH) — runtime/worker.py reads them
        # into the shared-memory augment ring / DevicePrefetcher
        env.update(job.input_spec.to_env())
        # spec.multislice → KFTPU_MULTISLICE_PIPELINE/_MICROBATCHES: the
        # MPMD pipeline-over-DCN path (one program per slice, explicit
        # activation transfers — runtime/worker.py,
        # parallel/multislice.py)
        env.update(job.multislice.to_env())
        # spec.kernels → KFTPU_KERNEL_ATTENTION/_OPTIMIZER/_SERVING: the
        # kernel tier (flash attention / fused-Adam update / int8
        # serving) — runtime/worker.py consumes them and bakes every set
        # knob into the recipe fingerprint + AOT step key
        env.update(job.kernels.to_env())
        # spec.integrity → KFTPU_INTEGRITY*: the numeric sentinel knobs
        # (runtime/sentinel.py). Deliberately EXCLUDED from the recipe
        # fingerprint — toggling detection must not invalidate warm
        # compile caches or AOT executables (the probe's program shape
        # is layout-gated, not integrity-gated).
        env.update(job.integrity.to_env())
        # anomaly-rollback directive → KFTPU_RESUME_STEP (pin the
        # restore to the LKG step, NOT the newest checkpoint — newest
        # may carry the corruption) and, when the operator armed
        # bisection on a repeat trip, KFTPU_REPLAY_RANGE (the worker
        # re-runs the suspect steps deterministically and publishes a
        # clean/reproduced verdict span)
        rollback = k8s.annotations_of(manifest).get(
            ANOMALY_ROLLBACK_ANNOTATION)
        if rollback:
            from ..runtime.sentinel import (REPLAY_RANGE_ENV,
                                            RESUME_STEP_ENV)
            try:
                directive = json.loads(rollback)
                lkg_step = int(directive.get("lkgStep", 0))
                replay_range = directive.get("replay")
            except (AttributeError, TypeError, ValueError):
                lkg_step, replay_range = 0, None
            if lkg_step > 0:
                env[RESUME_STEP_ENV] = str(lkg_step)
            if replay_range:
                env[REPLAY_RANGE_ENV] = str(replay_range)
        from ..runtime.compile_cache import (COMPILE_CACHE_ENV,
                                             SHARED_CACHE_ROOT_ENV,
                                             default_cache_dir,
                                             namespace_cache_dir)
        # cache-dir precedence: an explicit spec.compileCacheDir wins;
        # then the CLUSTER-SHARED compile-cache service (the operator
        # deployment carries KFTPU_SHARED_CACHE_ROOT, backed by the
        # tpu-compile-cache volume — every gang of a namespace shares
        # one cache, so the first job to compile a program warms every
        # later job/rebind/resize, not just its own pod restarts); then
        # the per-job default on the checkpoint volume
        shared_root = os.environ.get(SHARED_CACHE_ROOT_ENV, "")
        cache_dir = job.compile_cache_dir or (
            namespace_cache_dir(shared_root, job.namespace)
            if shared_root else "") or (
            default_cache_dir(job.checkpoint_dir)
            if job.checkpoint_dir else "")
        if cache_dir:
            # persistent XLA compilation cache on the checkpoint volume:
            # a restarted/warm-started gang skips the first-step compile
            # (runtime/compile_cache.py; BASELINE.md north-star #2)
            env[COMPILE_CACHE_ENV] = cache_dir
        # spec.warmStart → KFTPU_AOT / KFTPU_AOT_DIR: the serialized-
        # executable rung above the cache (runtime/aot.py). With AOT on
        # but no explicit dir, executables live beside the active cache
        # so a shared cache volume shares them across jobs too.
        env.update(job.warm_start.to_env())
        if job.warm_start.aot and not job.warm_start.aot_dir \
                and cache_dir:
            from ..runtime.aot import AOT_DIR_ENV, default_aot_dir
            volume = job.compile_cache_dir or (
                namespace_cache_dir(shared_root, job.namespace)
                if shared_root else job.checkpoint_dir)
            env.setdefault(AOT_DIR_ENV, default_aot_dir(volume))
        if env:
            self._add_env(pod, env)
        return pod

    def _add_env(self, pod: dict, env: dict[str, str]) -> None:
        for c in pod["spec"]["containers"]:
            cenv = c.setdefault("env", [])
            present = {e.get("name") for e in cenv}
            for k, v in env.items():
                if k not in present:
                    cenv.append({"name": k, "value": v})

    def _build_tpu_pod(self, job: TrainingJob, manifest: dict, rs: ReplicaSpec,
                       contract: TopologyContract, name: str,
                       rect: SliceRect | None = None) -> dict:
        pod = self._base_pod(job, manifest, rs, name, "TPU",
                             str(contract.process_id))
        spec = pod["spec"]
        # TPU placement: the node selectors GKE TPU node pools carry + the
        # extended resource request for this host's chips (the GPU-driver
        # DaemonSet slot of the reference, SURVEY.md §2.6).
        sel = spec.setdefault("nodeSelector", {})
        sel.setdefault("cloud.google.com/gke-tpu-accelerator",
                       f"tpu-{contract.slice_topology.generation.name}")
        if rect is not None:
            # slice-scheduler binding: pin to the ASSIGNED pool — the
            # pool's topology may be larger than the job's (a v5e-8 gang
            # carved out of a v5e-32 pool), so the pool label replaces
            # the exact-topology pin, and the rect rides along as a pod
            # annotation for operators/debuggers reading kubectl
            sel.setdefault(POOL_LABEL, rect.pool)
            pod["metadata"]["annotations"][
                "scheduling.kubeflow.org/slice"] = json.dumps(
                    rect.to_dict())
        else:
            sel.setdefault("cloud.google.com/gke-tpu-topology",
                           contract.slice_topology.name)
        for c in spec["containers"]:
            res = c.setdefault("resources", {})
            res.setdefault("limits", {})[TPU_RESOURCE] = \
                contract.slice_topology.chips_per_host
        # gang group: one group per job covering every slice of the replica
        group = f"{job.namespace}/{job.name}"
        pod["metadata"]["labels"][POD_GROUP_LABEL] = group.replace("/", ".")
        pod["metadata"]["annotations"]["scheduling.kubeflow.org/min-member"] = \
            str(rs.pod_count)
        env = contract.to_env()
        env["KFTPU_SHARDING"] = json.dumps(job.sharding.resolve(
            contract.slice_topology.num_chips * contract.num_slices))
        env["KFTPU_JOB_NAME"] = job.name
        env["KFTPU_JOB_KIND"] = job.kind
        self._add_env(pod, env)
        if job.kind in ("MPIJob", "ChainerJob"):
            self._add_env(pod, self._mpi_env(job))
        return pod

    def _build_replica_pod(self, job: TrainingJob, manifest: dict,
                           rs: ReplicaSpec, rtype: str, index: int,
                           name: str) -> dict:
        pod = self._base_pod(job, manifest, rs, name, rtype, str(index))
        if job.kind == "TFJob":
            self._add_env(pod, {"TF_CONFIG": json.dumps(
                self._tf_config(job, rtype, index))})
        elif job.kind == "PyTorchJob":
            self._add_env(pod, self._pytorch_env(job, rtype, index))
        elif job.kind in ("MPIJob", "ChainerJob"):
            # ChainerMN drives workers over MPI (chainer-operator.libsonnet
            # renders an mpiexec hostfile); same hostlist contract
            self._add_env(pod, self._mpi_env(job))
        elif job.kind == "MXJob":
            self._add_env(pod, self._mxnet_env(job, rtype, index))
        elif job.kind == "PaddleJob":
            self._add_env(pod, self._paddle_env(job, rtype, index))
        return pod

    # ---------------------------------------------------- legacy contracts

    def _addr(self, job: TrainingJob, pod_name: str, port: int = DEFAULT_PORT) -> str:
        return f"{pod_name}.{_workers_service_name(job)}.{job.namespace}:{port}"

    def _tf_config(self, job: TrainingJob, rtype: str, index: int) -> dict:
        """TF_CONFIG rendered the way tf-operator does (launcher.py:68-88
        consumes exactly this shape)."""
        cluster: dict[str, list[str]] = {}
        for t, rs in job.replica_specs.items():
            if t == "TPU":
                cluster["worker"] = [
                    self._addr(job, _tpu_pod_name(job, s, h))
                    for s in range(rs.num_slices)
                    for h in range(rs.topology.num_hosts)]
            else:
                cluster[t.lower()] = [
                    self._addr(job, _replica_pod_name(job, t, i))
                    for i in range(rs.replicas)]
        return {"cluster": cluster,
                "task": {"type": rtype.lower(), "index": index}}

    def _pytorch_env(self, job: TrainingJob, rtype: str, index: int) -> dict:
        master = _replica_pod_name(job, "Master", 0)
        world = job.total_pods()
        rank = 0 if rtype == "Master" else index + 1
        return {"MASTER_ADDR": f"{master}.{_workers_service_name(job)}.{job.namespace}",
                "MASTER_PORT": str(DEFAULT_PORT),
                "RANK": str(rank), "WORLD_SIZE": str(world)}

    def _mpi_env(self, job: TrainingJob) -> dict:
        """Hostlist env replacing the reference's kubectl-delivery hostfile
        (mpi-operator.libsonnet:116-135). Hosts come from the JOB's compute
        replicas — TPU gang if present, else Worker — the same list on
        every pod (launcher/master included)."""
        tpu = job.tpu_spec
        if tpu is not None and tpu.topology is not None:
            hosts = [_tpu_pod_name(job, s, h)
                     for s in range(tpu.num_slices)
                     for h in range(tpu.topology.num_hosts)]
        else:
            worker = job.replica_specs.get("Worker")
            hosts = [_replica_pod_name(job, "Worker", i)
                     for i in range(worker.replicas)] if worker else []
        fqdn = [f"{h}.{_workers_service_name(job)}.{job.namespace}" for h in hosts]
        return {"KFTPU_MPI_HOSTS": ",".join(fqdn),
                "KFTPU_MPI_NUM_HOSTS": str(len(fqdn))}

    def _mxnet_env(self, job: TrainingJob, rtype: str, index: int) -> dict:
        """DMLC env the way mxnet-operator renders it
        (mxnet-operator.libsonnet): one Scheduler roots the PS tracker."""
        scheduler = _replica_pod_name(job, "Scheduler", 0)
        counts = {t: rs.replicas for t, rs in job.replica_specs.items()}
        return {
            "DMLC_PS_ROOT_URI":
                f"{scheduler}.{_workers_service_name(job)}.{job.namespace}",
            "DMLC_PS_ROOT_PORT": str(DEFAULT_PORT),
            "DMLC_ROLE": rtype.lower(),
            "DMLC_NUM_SERVER": str(counts.get("Server", 0)),
            "DMLC_NUM_WORKER": str(counts.get("Worker", 0)),
        }

    def _paddle_env(self, job: TrainingJob, rtype: str, index: int) -> dict:
        """PADDLE_* env the way paddle-operator renders it
        (kubeflow/paddle-job/*.libsonnet): pserver endpoints + trainer id."""
        pservers = job.replica_specs.get("Pserver")
        endpoints = [
            self._addr(job, _replica_pod_name(job, "Pserver", i))
            for i in range(pservers.replicas)] if pservers else []
        trainers = job.replica_specs.get("Trainer")
        env = {
            "PADDLE_PSERVERS": ",".join(endpoints),
            "PADDLE_PSERVER_PORT": str(DEFAULT_PORT),
            "PADDLE_TRAINERS": str(trainers.replicas if trainers else 0),
            "PADDLE_TRAINING_ROLE":
                "PSERVER" if rtype == "Pserver" else "TRAINER",
        }
        if rtype == "Trainer":
            env["PADDLE_TRAINER_ID"] = str(index)
        return env

    # ------------------------------------------------------------- failure

    def _chief_pod_name(self, job: TrainingJob) -> str:
        # MXNet's Scheduler and Paddle's Pserver run until shutdown; job
        # completion is signaled by the first worker/trainer (the operator
        # semantics of mxnet-operator/paddle-operator)
        preferred = {"MXJob": ("Worker",),
                     "PaddleJob": ("Trainer",)}.get(job.kind, ())
        for t in (*preferred, "Chief", "Master", "Launcher", "Coordinator"):
            if t in job.replica_specs:
                return _replica_pod_name(job, t, 0)
        if job.tpu_spec is not None:
            return _tpu_pod_name(job, 0, 0)
        first = sorted(job.replica_specs)[0]
        return _replica_pod_name(job, first, 0)

    def _deadline_remaining(self, job: TrainingJob,
                            manifest: dict) -> float | None:
        """Seconds until activeDeadlineSeconds lands (negative = already
        over), or None when no deadline applies / the job never started."""
        deadline = job.run_policy.active_deadline_seconds
        if deadline is None:
            return None
        created = k8s.get_condition(manifest, COND_CREATED)
        if not created or created.get("status") != "True":
            return None
        try:
            started = float(created.get("lastTransitionTime") or 0)
        except (TypeError, ValueError):
            return None
        if not started:
            return None
        return started + deadline - _now()

    def _restart_backoff_remaining(self, manifest: dict) -> float:
        nb = k8s.annotations_of(manifest).get(RESTART_NOT_BEFORE_ANNOTATION)
        try:
            return float(nb) - _now() if nb else 0.0
        except (TypeError, ValueError):
            return 0.0

    @staticmethod
    def _beat_of(pod: dict | None) -> tuple[float, int] | None:
        """The pod's heartbeat (time, step), or None when absent or
        malformed — a bad annotation must degrade to "no heartbeat",
        never crash the reconcile loop."""
        if pod is None:
            return None
        raw = k8s.annotations_of(pod).get(HEARTBEAT_ANNOTATION)
        if not raw:
            return None
        try:
            d = json.loads(raw)
            beat = float(d.get("time", 0))
            step = int(d.get("step", 0))
        except (AttributeError, TypeError, ValueError):
            # AttributeError: valid JSON that isn't an object ("3",
            # "null")
            return None
        return (beat, step) if beat else None

    def _beat_age(self, namespace: str, pod_name: str, beat: float,
                  now: float) -> float:
        """Heartbeat staleness with the clock-skew clamp. A beat
        stamped in the FUTURE (worker clock ahead of the controller's)
        is clamped to the moment we first observed that value — without
        the clamp a hung worker with, say, an hour of skew reads as
        infinitely fresh for an hour and the stall watchdog never fires
        on time. A fresh (changing) beat clears the clamp state. The
        first-seen map is in-memory: a controller restart re-clamps a
        still-future beat to the restart time, delaying detection by at
        most one stall timeout — the safe direction."""
        key = (namespace, pod_name)
        if beat <= now:
            self._future_beats.pop(key, None)
            return now - beat
        seen = self._future_beats.get(key)
        if seen is None or seen[0] != beat:
            self._future_beats[key] = (beat, now)
            return 0.0
        return now - seen[1]

    def _stalled_chief(self, job: TrainingJob, manifest: dict,
                       by_name: dict[str, dict], chief: str) -> bool:
        """Whether the chief's heartbeat annotation is staler than
        runPolicy.stallTimeoutSeconds. A pod with NO heartbeat is never
        declared stalled (non-instrumented images must keep working)."""
        timeout = job.run_policy.stall_timeout_seconds
        if not timeout or k8s.condition_true(manifest, COND_RESTARTING):
            return False
        pod = by_name.get(chief)
        if pod is None or \
                pod.get("status", {}).get("phase") != POD_RUNNING:
            return False
        beat = self._beat_of(pod)
        if beat is None:
            return False
        return self._beat_age(job.namespace, chief, beat[0],
                              _now()) > timeout

    def _stalled_workers(self, job: TrainingJob, manifest: dict,
                         by_name: dict[str, dict],
                         tpu_names: list[str], chief: str) -> list[str]:
        """Per-worker stall: Running non-chief members whose heartbeat
        is staler than stallTimeoutSeconds. Catches the straggler
        failure mode the chief-only watchdog is blind to — one wedged
        worker, healthy chief (the chief keeps beating while the
        collective stalls inside the step). Same contract as the chief
        watchdog: no heartbeat, no verdict."""
        timeout = job.run_policy.stall_timeout_seconds
        if not timeout or k8s.condition_true(manifest, COND_RESTARTING):
            return []
        now = _now()
        stalled = []
        for name in tpu_names:
            if name == chief:
                continue
            pod = by_name.get(name)
            if pod is None or \
                    pod.get("status", {}).get("phase") != POD_RUNNING:
                continue
            beat = self._beat_of(pod)
            if beat is None:
                continue
            if self._beat_age(job.namespace, name, beat[0], now) > timeout:
                stalled.append(name)
        return stalled

    def _note_step_skew(self, job: TrainingJob, by_name: dict[str, dict],
                        tpu_names: list[str], chief: str,
                        client: KubeClient) -> None:
        """Straggler scoring from per-worker heartbeat steps: a worker
        whose FRESH heartbeat trails the chief's step by
        health.STEP_SKEW_MIN_STEPS on STEP_SKEW_STREAK consecutive
        reconciles folds one step-skew event into its host's health
        score (scheduler/health.py) — soft evidence that accumulates
        toward quarantine without tearing anything down. The max skew
        is exported as a gauge so dashboards see the straggler before
        the score moves."""
        now = _now()
        # freshness bound: the stall timeout when the job runs a
        # watchdog, the shared default otherwise — a STALE beat is a
        # hung worker (the watchdogs' business), not a slow host
        fresh_s = job.run_policy.stall_timeout_seconds or \
            health.STEP_SKEW_FRESH_S
        chief_beat = self._beat_of(by_name.get(chief))
        if chief_beat is None or \
                self._beat_age(job.namespace, chief,
                               chief_beat[0], now) > fresh_s:
            return
        max_skew = 0
        for name in tpu_names:
            if name == chief:
                continue
            key = (job.namespace, job.name, name)
            beat = self._beat_of(by_name.get(name))
            fresh = beat is not None and self._beat_age(
                job.namespace, name, beat[0], now) <= fresh_s
            skew = (chief_beat[1] - beat[1]) if fresh else 0
            if not fresh or skew < health.STEP_SKEW_MIN_STEPS:
                self._skew_streak.pop(key, None)
                continue
            max_skew = max(max_skew, skew)
            streak = self._skew_streak.get(key, 0) + 1
            if streak >= health.STEP_SKEW_STREAK:
                self._skew_streak[key] = 0
                node = by_name[name].get("spec", {}).get("nodeName")
                if node:
                    health.record_host_event(
                        client, node, health.EVENT_STEP_SKEW,
                        job_key=f"{job.namespace}/{job.name}")
            else:
                self._skew_streak[key] = streak
        obsreg.gauge(
            "kftpu_job_step_skew",
            "chief step minus the slowest worker's heartbeat step",
            labels=("namespace", "name")).labels(
                namespace=job.namespace, name=job.name).set(max_skew)

    @staticmethod
    def _suspect_node(by_name: dict[str, dict],
                      pod_names: list[str]) -> str | None:
        """The single host a failure is attributable to: every failed/
        stalled pod ran on the same node. Multi-host failures (a whole
        pool losing power, a fleet preemption) attribute to nobody —
        migrating off one host would not help."""
        nodes = {by_name[n].get("spec", {}).get("nodeName")
                 for n in pod_names if n in by_name}
        nodes.discard(None)
        nodes.discard("")
        return nodes.pop() if len(nodes) == 1 else None

    # ------------------------------------------------- numeric integrity

    @staticmethod
    def _anomaly_of(by_name: dict[str, dict], failed: list[str]):
        """(pod_name, AnomalyEvidence) from the first failed pod carrying
        a parseable sentinel evidence annotation, else (None, None).
        Evidence is on the POD (the worker annotates itself before
        exiting) so it survives the worker process and arrives with the
        same Failed phase the reconcile loop already watches."""
        from ..runtime.sentinel import AnomalyEvidence
        for name in failed:
            pod = by_name.get(name)
            if pod is None:
                continue
            raw = k8s.annotations_of(pod).get(ANOMALY_ANNOTATION)
            if not raw:
                continue
            ev = AnomalyEvidence.from_json(raw)
            if ev is not None:
                return name, ev
        return None, None

    def _handle_anomaly(self, client: KubeClient, job: TrainingJob,
                        manifest: dict, pods: list[dict],
                        failed: list[str], anomaly,
                        suspect: str | None = None) -> Result:
        """The LKG rollback path. A sentinel trip is a DELIBERATE exit,
        not a crash: the rollback budget (runPolicy.maxAnomalyRollbacks)
        is separate from backoffLimit and the gang restart does not
        count against it. The rollback directive annotation pins the
        recreated gang's restore to the last-known-good step (not the
        newest checkpoint, which may carry the corruption), and a SECOND
        trip over the same LKG arms the replay-bisection window — the
        worker re-runs the suspect step range deterministically with the
        suspect host's health event already folded, converting "this job
        is cursed" into "host N is bad"."""
        anns = k8s.annotations_of(manifest)
        count = int(anns.get(ANOMALY_COUNT_ANNOTATION, "0"))
        budget = job.run_policy.max_anomaly_rollbacks
        summary = (f"{anomaly.kind} at step {anomaly.step} "
                   f"(lkg {anomaly.lkg})")
        if count >= budget:
            self._set_condition(
                client, manifest, COND_FAILED, "True",
                "AnomalyBudgetExceeded",
                f"numeric anomaly {summary}; rolled back {count} times "
                f"(runPolicy.maxAnomalyRollbacks={budget})")
            self._cleanup_pods(client, job, pods)
            return Result()
        # replay bisection arms on the SECOND trip against the same LKG:
        # same range re-failing means the fault reproduces — re-run it
        # deterministically and let the verdict blame (or clear) the host
        prev_lkg = None
        try:
            prev = json.loads(anns.get(ANOMALY_ROLLBACK_ANNOTATION) or "")
            prev_lkg = int(prev.get("lkgStep"))
        except (AttributeError, TypeError, ValueError):
            prev_lkg = None
        lkg = int(anomaly.lkg or 0)
        replay = (f"{lkg}:{int(anomaly.step)}"
                  if prev_lkg is not None and prev_lkg == lkg
                  and int(anomaly.step) > lkg else None)
        for p in pods:
            try:
                client.delete("v1", "Pod", k8s.namespace_of(p, job.namespace),
                              k8s.name_of(p))
            except NotFoundError:
                pass
        applied = {"count": count}

        def _mutate(obj: dict) -> dict | None:
            fresh = int(k8s.annotations_of(obj).get(
                ANOMALY_COUNT_ANNOTATION, "0"))
            applied["count"] = fresh
            directive: dict = {"lkgStep": lkg,
                               "tripStep": int(anomaly.step),
                               "kind": anomaly.kind,
                               "count": fresh + 1}
            if replay:
                directive["replay"] = replay
            updates = {ANOMALY_COUNT_ANNOTATION: str(fresh + 1),
                       ANOMALY_ROLLBACK_ANNOTATION: json.dumps(directive)}
            if suspect and job.scheduling_policy is not None:
                # same failure-domain contract as crash restarts: the
                # scheduler replans the rebind excluding the SDC suspect
                updates[SUSPECT_ANNOTATION] = suspect
            apply_annotations(obj, updates)
            if job.checkpoint_dir and \
                    not obj.setdefault("spec", {}).get("resumeFrom"):
                obj["spec"]["resumeFrom"] = job.checkpoint_dir
            return obj

        try:
            patched = update_with_conflict_retry(
                client, *k8s.key_of(manifest), _mutate)
        except NotFoundError:
            return Result()
        if suspect:
            # SDC evidence onto the host the anomalous worker ran on:
            # two trips cross health's quarantine threshold, so a
            # repeat-offender host drains out of the placement pool
            health.record_host_event(
                client, suspect, health.EVENT_NUMERIC_ANOMALY,
                job_key=f"{job.namespace}/{job.name}", now=_now())
        obsreg.counter(
            "kftpu_gang_restarts_total",
            "whole-gang restarts by trigger (failed pod, vanish, resize, "
            "stall)", labels=("kind", "reason")).labels(
                kind=self.kind, reason="NumericAnomaly").inc()
        used = applied["count"] + 1
        mode = f", replaying {replay} for bisection" if replay else ""
        self._trace_event(patched, "anomaly-rollback", kind=anomaly.kind,
                          step=int(anomaly.step), lkg=lkg, count=used,
                          **({"replay": replay} if replay else {}),
                          **({"suspect": suspect} if suspect else {}))
        self._set_condition(
            client, patched, COND_RESTARTING, "True", "NumericAnomaly",
            f"{summary}: rolling back to LKG step {lkg} "
            f"({used}/{budget} rollbacks){mode}")
        return Result(requeue=True)

    def _clear_rollback_annotation(self, client: KubeClient,
                                   job: TrainingJob, manifest: dict,
                                   by_name: dict[str, dict],
                                   chief: str) -> None:
        """Consume the rollback directive once the chief's FRESH
        heartbeat shows training advanced past the trip step — the
        suspect range re-ran clean, so future restarts must resume from
        the newest checkpoint, not stay pinned to the old LKG."""
        raw = k8s.annotations_of(manifest).get(ANOMALY_ROLLBACK_ANNOTATION)
        if not raw:
            return
        try:
            trip = int(json.loads(raw).get("tripStep", 0))
        except (AttributeError, TypeError, ValueError):
            trip = 0
        beat = self._beat_of(by_name.get(chief))
        if beat is None:
            return
        fresh_s = job.run_policy.stall_timeout_seconds or \
            health.STEP_SKEW_FRESH_S
        if self._beat_age(job.namespace, chief, beat[0], _now()) > fresh_s \
                or beat[1] <= trip:
            return
        try:
            update_with_conflict_retry(
                client, *k8s.key_of(manifest),
                lambda obj: apply_annotations(
                    obj, {ANOMALY_ROLLBACK_ANNOTATION: None})
                if ANOMALY_ROLLBACK_ANNOTATION in k8s.annotations_of(obj)
                else None)
        except NotFoundError:
            pass

    def _note_numeric_health(self, job: TrainingJob,
                             by_name: dict[str, dict],
                             tpu_names: list[str],
                             client: KubeClient) -> None:
        """The heartbeat numeric canary: a worker whose FRESH heartbeat
        reports a non-finite lastLoss/lastGradNorm gets flagged (host
        health event + anomaly counter) even when spec.integrity is
        disabled — the payload rides the liveness beat for free, so
        non-instrumented detection costs nothing extra. Freshness is
        clamped the same way the stall watchdog's is (PR 6): a stale or
        future-stamped beat is not evidence."""
        now = _now()
        fresh_s = job.run_policy.stall_timeout_seconds or \
            health.STEP_SKEW_FRESH_S
        for name in tpu_names:
            pod = by_name.get(name)
            if pod is None:
                continue
            raw = k8s.annotations_of(pod).get(HEARTBEAT_ANNOTATION)
            if not raw:
                continue
            try:
                d = json.loads(raw)
                beat = float(d.get("time", 0))
                step = int(d.get("step", 0))
            except (AttributeError, TypeError, ValueError):
                continue
            if not beat or self._beat_age(job.namespace, name,
                                          beat, now) > fresh_s:
                continue
            bad = None
            for field in ("lastLoss", "lastGradNorm"):
                v = d.get(field)
                if v is None:
                    continue
                try:
                    val = float(v)
                except (TypeError, ValueError):
                    continue
                if not math.isfinite(val):
                    bad = (field, v)
                    break
            key = (job.namespace, name)
            if bad is None:
                continue
            if self._numeric_flagged.get(key) == step:
                continue
            self._numeric_flagged[key] = step
            log.warning("pod %s/%s heartbeat reports non-finite %s=%s "
                        "at step %d", job.namespace, name, bad[0], bad[1],
                        step)
            from ..runtime.sentinel import KIND_HEARTBEAT_NAN, \
                anomaly_counter
            anomaly_counter().labels(kind=KIND_HEARTBEAT_NAN).inc()
            node = pod.get("spec", {}).get("nodeName")
            if node:
                health.record_host_event(
                    client, node, health.EVENT_NUMERIC_ANOMALY,
                    job_key=f"{job.namespace}/{job.name}", now=now)

    def _handle_gang_failure(self, client: KubeClient, job: TrainingJob,
                             manifest: dict, pods: list[dict],
                             failed: list[str],
                             reason: str = "GangRestart",
                             count_restart: bool = True,
                             suspect: str | None = None,
                             evidence: str | None = None) -> Result:
        restarts = int(k8s.annotations_of(manifest).get(
            RESTART_COUNT_ANNOTATION, "0"))
        if count_restart and restarts >= job.run_policy.backoff_limit:
            self._set_condition(
                client, manifest, COND_FAILED, "True", "BackoffLimitExceeded",
                f"pods {failed} failed; gang restarted {restarts} times")
            self._cleanup_pods(client, job, pods)
            return Result()
        # Gang restart: delete every pod of the job (the slice is the failure
        # domain), bump the restart counter, requeue to recreate.
        for p in pods:
            try:
                client.delete("v1", "Pod", k8s.namespace_of(p, job.namespace),
                              k8s.name_of(p))
            except NotFoundError:
                pass
        rp = job.run_policy
        # mutable cell: the RMW below recomputes restarts/delay from the
        # FRESH object each attempt, and the tail of this method needs
        # the values the WINNING attempt actually wrote
        applied = {"restarts": restarts, "delay": 0.0}

        def _mutate(obj: dict) -> dict | None:
            # recompute from the fresh read: a concurrent writer (the
            # scheduler's binding/state rewrites, a sibling operator
            # replica in a brief two-leader window) may have landed
            # between our reconcile-start read and this write — the
            # blind patch this replaces silently double-counted or lost
            # the restart counter in exactly that interleaving
            fresh_restarts = int(k8s.annotations_of(obj).get(
                RESTART_COUNT_ANNOTATION, "0"))
            applied["restarts"] = fresh_restarts
            updates: dict = {}
            if count_restart:
                updates[RESTART_COUNT_ANNOTATION] = str(fresh_restarts + 1)
            if suspect and job.scheduling_policy is not None:
                # failure-domain-aware rebind: record the host this
                # teardown is attributable to; the scheduler replans the
                # binding EXCLUDING its cells (scheduler/core.py) so the
                # gang migrates instead of crash-looping in place
                updates[SUSPECT_ANNOTATION] = suspect
            applied["delay"] = 0.0
            if count_restart and rp.restart_backoff_seconds > 0:
                # exponential backoff + deterministic jitter (seeded by
                # job identity and attempt, so reconcile retries compute
                # the same schedule): spreads a fleet-wide preemption's
                # restarts out instead of stampeding the apiserver
                d = min(rp.restart_backoff_seconds * (2 ** fresh_restarts),
                        rp.restart_backoff_max_seconds)
                d *= random.Random(
                    f"{job.namespace}/{job.name}:"
                    f"{fresh_restarts}").uniform(1.0, 1.5)
                applied["delay"] = d
                updates[RESTART_NOT_BEFORE_ANNOTATION] = \
                    f"{_now() + d:.3f}"
            dirty = bool(updates)
            apply_annotations(obj, updates)
            if job.checkpoint_dir and \
                    not obj.setdefault("spec", {}).get("resumeFrom"):
                # close the resume loop: the recreated gang restores from
                # the job's own checkpoints and continues from the last
                # step (SURVEY §5 — checkpoint-resume makes gang
                # restarts cheap)
                obj["spec"]["resumeFrom"] = job.checkpoint_dir
                dirty = True
            return obj if dirty else None

        try:
            patched = update_with_conflict_retry(
                client, *k8s.key_of(manifest), _mutate)
        except NotFoundError:
            return Result()   # job deleted mid-teardown: nothing to restart
        restarts, delay = applied["restarts"], applied["delay"]
        if suspect and evidence:
            # fold the failure into the host's health score (the
            # quarantine feedback loop); best-effort by contract —
            # evidence must never block the restart itself
            health.record_host_event(client, suspect, evidence,
                                     job_key=f"{job.namespace}/{job.name}",
                                     now=_now())
        # counted AFTER the deletes/patch succeeded: a transient error in
        # the side effects above requeues and re-runs this path, and the
        # retry must not read as a second restart
        obsreg.counter(
            "kftpu_gang_restarts_total",
            "whole-gang restarts by trigger (failed pod, vanish, resize, "
            "stall)", labels=("kind", "reason")).labels(
                kind=self.kind, reason=reason).inc()
        budget = (f" ({restarts + 1}/{job.run_policy.backoff_limit})"
                  if count_restart else " (not counted against backoff)")
        wait = f", next attempt in {delay:.1f}s" if delay else ""
        self._set_condition(
            client, patched, COND_RESTARTING, "True", reason,
            f"pods {failed}: restarting whole gang{budget}{wait}")
        return Result(requeue_after=delay) if delay else Result(requeue=True)

    def _handle_finished(self, client: KubeClient, job: TrainingJob,
                         manifest: dict) -> Result:
        """ttlSecondsAfterFinished: reap the finished job object (and its
        children via cascade) once the TTL passes — measured from the
        terminal condition's transition time."""
        ttl = job.run_policy.ttl_seconds_after_finished
        if ttl is None:
            return Result()
        cond = k8s.get_condition(manifest, COND_SUCCEEDED)
        if not (cond and cond.get("status") == "True"):
            cond = k8s.get_condition(manifest, COND_FAILED)
        try:
            finished = float((cond or {}).get("lastTransitionTime") or 0)
        except (TypeError, ValueError):
            finished = 0.0
        if not finished:
            return Result()
        remaining = finished + ttl - _now()
        if remaining > 0:
            return Result(requeue_after=remaining)
        log.info("job %s/%s finished %ds ago (> ttl %ds): deleting",
                 job.namespace, job.name, int(_now() - finished), ttl)
        try:
            client.delete(*k8s.key_of(manifest))
        except NotFoundError:
            pass
        return Result()

    def _gc_orphans(self, client: KubeClient, namespace: str,
                    name: str) -> None:
        """Reap children whose owner job no longer exists. The job-name
        selector is the ownership scope (the same labels _base_pod
        stamps); everything matching it after the owner's deletion is
        an orphan pinning chips — delete it, count it."""
        selector = {"kubeflow.org/job-name": name,
                    "kubeflow.org/job-kind": self.kind.lower()}
        reaped = 0
        for kind_av in (("v1", "Pod"), ("v1", "Service")):
            for obj in client.list(*kind_av, namespace,
                                   selector=selector):
                try:
                    client.delete(*kind_av,
                                  k8s.namespace_of(obj, namespace),
                                  k8s.name_of(obj))
                    reaped += 1
                except NotFoundError:
                    pass
        if reaped:
            obsreg.counter(
                "kftpu_orphan_pods_gced_total",
                "orphaned gang children reaped after their owner job "
                "vanished (crash-consistency GC)",
                labels=("kind",)).labels(kind=self.kind).inc(reaped)
            log.info("gc: reaped %d orphaned children of %s/%s",
                     reaped, namespace, name)

    def _cleanup_pods(self, client: KubeClient, job: TrainingJob,
                      pods: list[dict]) -> None:
        """Reap pods per cleanPodPolicy: Running keeps terminal pods for
        debugging, All reaps everything, None keeps everything."""
        policy = job.run_policy.clean_pod_policy
        if policy == CLEAN_POD_NONE:
            return
        for p in pods:
            phase = p.get("status", {}).get("phase")
            if policy == CLEAN_POD_RUNNING and phase not in (POD_RUNNING, None,
                                                             "Pending"):
                continue
            if policy not in (CLEAN_POD_ALL, CLEAN_POD_RUNNING):
                continue
            try:
                client.delete("v1", "Pod", k8s.namespace_of(p, job.namespace),
                              k8s.name_of(p))
            except NotFoundError:
                pass

    # --------------------------------------------------------------- status

    def _set_condition(self, client: KubeClient, manifest: dict, ctype: str,
                       status: str, reason: str, message: str) -> None:
        fresh = client.get_or_none(*k8s.key_of(manifest)) or manifest
        existing = k8s.get_condition(fresh, ctype)
        if existing and existing.get("status") == status and \
                existing.get("reason") == reason and \
                existing.get("message") == message:
            manifest["status"] = fresh.get("status", {})
            return  # idempotent: no write, no MODIFIED event, no requeue loop
        k8s.set_condition(fresh, k8s.Condition(ctype, status, reason, message))
        client.update_status(fresh)
        manifest["status"] = fresh["status"]
        # observability rides the idempotence guard: a condition TRANSITION
        # is exactly one trace event (queued/created/running/succeeded/...)
        # and one metrics update — steady-state reconciles emit nothing
        self._trace_event(
            manifest,
            ctype.lower() if status == "True" else f"{ctype.lower()}-cleared",
            reason=reason, message=message)
        if status == "True" and ctype in (COND_SUCCEEDED, COND_FAILED):
            obsreg.counter(
                "kftpu_jobs_finished_total",
                "training jobs reaching a terminal condition",
                labels=("kind", "condition")).labels(
                    kind=self.kind, condition=ctype).inc()
            self._finalize_ledger(client, fresh)
        self._export_phase((k8s.namespace_of(manifest, "default"),
                            k8s.name_of(manifest)), manifest)

    def _finalize_ledger(self, client: KubeClient, manifest: dict) -> None:
        """On the terminal transition: fold the job's span stream into
        its final goodput ledger (obs/goodput.py) — stamped as the
        goodput annotation so the decomposition survives span-sink
        rotation, and exported as the kftpu_job_goodput_ratio /
        kftpu_job_badput_seconds_total gauges. Rides the _set_condition
        idempotence guard, so it runs exactly once per completion.
        Best-effort by contract: accounting must never fail the job it
        accounts for."""
        try:
            from ..obs.goodput import (GOODPUT_ANNOTATION,
                                       annotation_payload,
                                       export_job_ledger, ledger_for)
            span_path = os.environ.get(SPAN_PATH_ENV)
            trace_id = k8s.annotations_of(manifest).get(TRACE_ID_ANNOTATION)
            if not span_path or not trace_id:
                return
            ledger = ledger_for(span_path, trace_id)
            if not ledger["wallSeconds"]:
                return
            namespace = k8s.namespace_of(manifest, "default")
            name = k8s.name_of(manifest)
            export_job_ledger(namespace, name, ledger)
            # conflict-safe: the terminal transition window is busy
            # (scheduler state writes, TTL bookkeeping) — the final
            # ledger must neither lose nor clobber a concurrent write
            update_with_conflict_retry(
                client, *k8s.key_of(manifest),
                lambda obj: apply_annotations(obj, {
                    GOODPUT_ANNOTATION: annotation_payload(ledger)}))
            self._trace_event(manifest, "goodput-ledger",
                              goodput_ratio=ledger["goodputRatio"],
                              wall_seconds=ledger["wallSeconds"])
        except Exception as e:  # noqa: BLE001 — accounting is best-effort
            log.warning("final goodput ledger for %s failed: %s",
                        k8s.name_of(manifest), e)

    def _finalize_status(self, client: KubeClient, manifest: dict,
                         pods: list[dict], *, all_running: bool) -> None:
        """Steady-state status tail: the Running condition AND the
        replicaStatuses counts in ONE get+put per reconcile pass (the
        single-update-per-reconcile idiom — two sequential get+puts race
        with concurrent writers and double the apiserver traffic)."""
        counts: dict[str, dict[str, int]] = {}
        for p in pods:
            rtype = k8s.labels_of(p).get(REPLICA_TYPE_LABEL, "unknown")
            phase = p.get("status", {}).get("phase", "Pending")
            bucket = {"Running": "active", "Pending": "active",
                      "Succeeded": "succeeded", "Failed": "failed"}.get(
                          phase, "active")
            counts.setdefault(rtype, {"active": 0, "succeeded": 0,
                                      "failed": 0})[bucket] += 1
        fresh = client.get_or_none(*k8s.key_of(manifest))
        if fresh is None:
            return
        dirty = False
        if all_running:
            existing = k8s.get_condition(fresh, COND_RUNNING)
            if not (existing and existing.get("status") == "True" and
                    existing.get("reason") == "JobRunning"):
                k8s.set_condition(fresh, k8s.Condition(
                    COND_RUNNING, "True", "JobRunning",
                    "all replicas running"))
                dirty = True
                # the Running TRANSITION (guarded above) is the
                # pod-start→running edge of the job's trace timeline
                self._trace_event(fresh, "running", reason="JobRunning")
                self._export_phase((k8s.namespace_of(fresh, "default"),
                                    k8s.name_of(fresh)), fresh)
        if fresh.get("status", {}).get("replicaStatuses") != counts:
            fresh.setdefault("status", {})["replicaStatuses"] = counts
            dirty = True
        if dirty:
            client.update_status(fresh)
        manifest["status"] = fresh.get("status", {})


def all_reconcilers() -> list[TrainingJobReconciler]:
    return [TrainingJobReconciler(k) for k in JOB_KINDS]
