"""Reconcilers: the runtime control plane.

- ``runtime``: the controller manager (watch → workqueue → reconcile), the
  controller-runtime analog every reconciler plugs into.
- ``tpujob``: the training-job operator — gang-scheduled TPU slices,
  topology-contract injection, slice-level failure handling.
- ``statefulset``: minimal built-in STS → pods reconciler (the
  kube-controller-manager piece the in-memory control plane needs).
- ``notebook``: Notebook CR → StatefulSet + Service + VirtualService.
- ``profile``: Profile CR → Namespace + ServiceAccounts + RoleBindings.
- ``admission``: PodDefault mutating-webhook logic.
"""

from typing import Optional


def build_manager(client, vizier=None, vizier_url: Optional[str] = None):
    """Assemble the full control plane over one client: training operators
    (all four job kinds), workflows, kubebench, katib, notebooks, profiles,
    statefulsets — plus the PodDefault admission hook when the client
    exposes an admission point (FakeCluster does; a real apiserver gets the
    webhook via manifests instead)."""
    from ..katib.studyjob import StudyJobCompatReconciler
    from ..scheduler.core import SliceScheduler
    from ..workflows.engine import WorkflowReconciler
    from ..workflows.kubebench import KubebenchJobReconciler
    from .admission import PodDefaultsWebhook
    from .experiment import ExperimentReconciler
    from .notebook import NotebookReconciler
    from .profile import ProfileReconciler
    from .runtime import Manager
    from .statefulset import StatefulSetReconciler
    from .tpujob import all_reconcilers

    mgr = Manager(client)
    # the slice scheduler runs ahead of the operators: it binds
    # scheduler-managed TPUJobs to slices; jobs without a
    # schedulingPolicy bypass it entirely
    mgr.add(SliceScheduler())
    for r in all_reconcilers():
        mgr.add(r)
    mgr.add(StatefulSetReconciler())
    mgr.add(NotebookReconciler())
    mgr.add(ProfileReconciler())
    mgr.add(WorkflowReconciler())
    mgr.add(KubebenchJobReconciler())
    mgr.add(ExperimentReconciler())
    # legacy StudyJob objects convert into owned Experiments; vizier=/
    # vizier_url= are accepted (and ignored) for caller compatibility
    mgr.add(StudyJobCompatReconciler(vizier=vizier, vizier_url=vizier_url))
    if hasattr(client, "admission_hooks"):
        client.admission_hooks.append(PodDefaultsWebhook(client))
    return mgr
