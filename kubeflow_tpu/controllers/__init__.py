"""Reconcilers: the runtime control plane.

- ``runtime``: the controller manager (watch → workqueue → reconcile), the
  controller-runtime analog every reconciler plugs into.
- ``tpujob``: the training-job operator — gang-scheduled TPU slices,
  topology-contract injection, slice-level failure handling.
- ``notebook``: Notebook CR → StatefulSet + Service + VirtualService.
- ``profile``: Profile CR → Namespace + ServiceAccounts + RoleBindings.
- ``admission``: PodDefault mutating-webhook logic.
"""
