"""The deployable controller-manager process.

``python -m kubeflow_tpu.controllers --kubeconfig <path>`` runs every
reconciler the framework ships against a real apiserver over
HttpKubeClient — the analog of the reference's controller binaries
(components/notebook-controller/cmd/manager/main.go, profile-controller,
tf-operator Deployment in tf-job-operator.libsonnet:148-179) collapsed
into one manager the way controller-runtime managers host many
controllers.

Without --kubeconfig it serves an in-memory FakeCluster (useful only with
--serve, which exposes that cluster over the wire for other processes).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading

from ..cluster.fake import FakeCluster
from .runtime import Manager

log = logging.getLogger(__name__)

# name → zero-arg factory; --controllers selects a subset
CONTROLLER_FACTORIES = {}


def _register_defaults() -> None:
    from ..katib.studyjob import StudyJobCompatReconciler
    from ..workflows.engine import WorkflowReconciler
    from .experiment import ExperimentReconciler
    from .notebook import NotebookReconciler
    from .profile import ProfileReconciler
    from .statefulset import StatefulSetReconciler
    from .tpujob import TrainingJobReconciler

    from ..api.trainingjob import JOB_KINDS
    for kind in JOB_KINDS:
        CONTROLLER_FACTORIES[kind.lower()] = (
            lambda k=kind: TrainingJobReconciler(k))
    from ..pipelines.scheduled import ScheduledWorkflowReconciler
    from ..scheduler.core import SliceScheduler
    from .application import ApplicationReconciler

    from .autoscaler import ServingFleetReconciler

    CONTROLLER_FACTORIES["application"] = ApplicationReconciler
    CONTROLLER_FACTORIES["scheduler"] = SliceScheduler
    CONTROLLER_FACTORIES["autoscaler"] = ServingFleetReconciler
    CONTROLLER_FACTORIES["notebook"] = NotebookReconciler
    CONTROLLER_FACTORIES["profile"] = ProfileReconciler
    CONTROLLER_FACTORIES["statefulset"] = StatefulSetReconciler
    CONTROLLER_FACTORIES["workflow"] = WorkflowReconciler
    CONTROLLER_FACTORIES["experiment"] = ExperimentReconciler
    # legacy StudyJob objects convert to Experiments (one search API)
    CONTROLLER_FACTORIES["studyjob"] = StudyJobCompatReconciler
    CONTROLLER_FACTORIES["scheduledworkflow"] = ScheduledWorkflowReconciler


def build_manager(client, controllers: list[str],
                  store_path: str = "", elector=None) -> Manager:
    """``elector`` (cluster/lease.py LeaderElector) gates EVERY hosted
    controller on one lease: the deployed unit of failover is the
    manager process, so all its controllers lead or follow together."""
    _register_defaults()
    mgr = Manager(client)
    kwargs = {"elector": elector} if elector is not None else {}
    for name in controllers:
        if name == "persistenceagent":
            # needs the run store (pipeline-apiserver shares the same file)
            from ..pipelines.store import PersistenceAgent, RunStore
            mgr.add(PersistenceAgent(RunStore(store_path or ":memory:")),
                    **kwargs)
            continue
        factory = CONTROLLER_FACTORIES.get(name)
        if factory is None:
            raise SystemExit(
                f"unknown controller {name!r}; "
                f"available: {sorted(CONTROLLER_FACTORIES) + ['persistenceagent']}")
        mgr.add(factory(), **kwargs)
    return mgr


def main(argv=None) -> int:
    _register_defaults()
    p = argparse.ArgumentParser(
        "kubeflow-tpu-manager",
        description="run the controller manager against an apiserver")
    p.add_argument("--kubeconfig", default="",
                   help="kubeconfig for the target apiserver (required "
                        "unless --fake)")
    p.add_argument("--context", default="",
                   help="kubeconfig context override")
    p.add_argument("--controllers",
                   default=",".join(sorted(CONTROLLER_FACTORIES)),
                   help="comma-separated subset to run")
    p.add_argument("--fake", action="store_true",
                   help="run over an in-memory cluster (demo/testing)")
    p.add_argument("--store", default="",
                   help="run-store sqlite path (persistenceagent)")
    p.add_argument("--metrics-port", type=int,
                   default=int(os.environ.get("KFTPU_METRICS_PORT", "0")),
                   help="serve /metrics (+/healthz) for Prometheus on "
                        "this port (0 = off; env fallback "
                        "KFTPU_METRICS_PORT) — the scrape surface the "
                        "tpu-job-operator / tpu-scheduler manifests "
                        "annotate")
    p.add_argument("--leader-elect", action="store_true",
                   help="run behind a coordination.k8s.io Lease "
                        "(cluster/lease.py): every replica watches, "
                        "only the lease holder writes — the HA "
                        "replicas: 2 deployments render this flag "
                        "(docs/operations.md 'Control-plane HA')")
    p.add_argument("--lease-name", default="kubeflow-tpu-manager",
                   help="Lease object name (one per Deployment; the "
                        "manifests pass the component's lease)")
    p.add_argument("--lease-namespace", default="kubeflow",
                   help="namespace the Lease lives in")
    p.add_argument("--lease-duration", type=float, default=15.0,
                   help="seconds a leader may go un-renewed before a "
                        "standby steals the lease (failover bound)")
    p.add_argument("--identity",
                   default=os.environ.get("KFTPU_POD_NAME", ""),
                   help="this replica's lease identity (default: "
                        "KFTPU_POD_NAME, else hostname.pid)")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        force=True)

    if args.kubeconfig:
        from ..cluster.http_client import HttpKubeClient
        client = HttpKubeClient.from_kubeconfig(
            args.kubeconfig, context=args.context or None)
    elif args.fake:
        client = FakeCluster()
    else:
        p.error("--kubeconfig is required (or --fake)")

    names = [c.strip() for c in args.controllers.split(",") if c.strip()]
    elector = None
    if args.leader_elect:
        import socket

        from ..cluster.lease import FencedKubeClient, LeaderElector
        identity = args.identity or f"{socket.gethostname()}.{os.getpid()}"
        # the elector renews through the RAW client (fencing the lease
        # writes themselves would deadlock re-election); everything the
        # CONTROLLERS write goes through the fence — a deposed leader's
        # in-flight reconcile dies at the client boundary, it cannot
        # race its successor (the second, independent line of defense
        # behind the pop-time leader gate)
        elector = LeaderElector(
            client=client, identity=identity, name=args.lease_name,
            namespace=args.lease_namespace,
            duration_s=args.lease_duration)
        client = FencedKubeClient(client, elector)
        log.info("leader election on: lease %s/%s identity %s "
                 "(duration %.1fs)", args.lease_namespace,
                 args.lease_name, identity, args.lease_duration)
    mgr = build_manager(client, names, store_path=args.store,
                        elector=elector)
    obs_server = None
    if args.metrics_port:
        from ..obs.http import ObsServer
        obs_server = ObsServer(port=args.metrics_port)
        log.info("metrics on :%d/metrics", obs_server.start())
    log.info("manager running %d controllers: %s", len(mgr.controllers),
             ", ".join(names))
    mgr.start_all()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    log.info("shutting down")
    mgr.stop_all()
    if elector is not None:
        # graceful handoff: clear the lease so the standby takes over
        # NOW instead of waiting out the lease duration
        elector.release()
    if obs_server is not None:
        obs_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
