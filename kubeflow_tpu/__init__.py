"""kubeflow_tpu — a TPU-native ML platform.

A ground-up rebuild of the capabilities of the Kubeflow v0.5 monorepo
(reference: kubeflow/kubeflow), designed TPU-first:

- ``api``         — typed object model: KfDef platform config, TPUJob/Notebook/
                    Profile/PodDefault/StudyJob/KubebenchJob CRD types, and a
                    lightweight Kubernetes object representation.
- ``kfctl``       — the deployment CLI (init/generate/apply/delete/show) and its
                    coordinator over platform drivers + the manifest engine.
- ``manifests``   — the package registry: programmatic manifest builders replacing
                    the reference's ksonnet prototypes (reference: kubeflow/ dir).
- ``cluster``     — Kubernetes API abstraction + in-memory apiserver (the envtest
                    analog used to test every controller without a cluster).
- ``controllers`` — reconcilers: the TPUJob operator (gang-scheduled TPU slices),
                    notebook, profile, admission webhook, application.
- ``runtime``     — the in-pod JAX worker runtime: distributed bootstrap, mesh
                    construction from slice topology, train-step engine, orbax
                    checkpointing, metrics + profiler hooks.
- ``parallel``    — parallelism as data: DP/TP/PP/SP(CP)/EP sharding specs lowered
                    to jax.sharding over a Mesh, pipeline microbatching, ring
                    collectives.
- ``ops``         — Pallas TPU kernels (ring attention, flash attention, ...).
- ``models``      — built-in workloads (ResNet-50 benchmark model, Transformer LM).
- ``serving``     — TPU-backed model server + HTTP front (reference:
                    components/k8s-model-server).
- ``katib``       — hyperparameter search (suggestions + study controller).
- ``kubebench``   — benchmark harness (configurator -> run -> reporter).
- ``dashboard``   — central dashboard backend API.
"""

__version__ = "0.1.0"
