"""Ecosystem catalog: alt serving stacks + data/gitops/build packages.

Reference packages with no analog until now (r2 verdict missing #7/#9):
kubeflow/{openvino,nvidia-inference-server,modeldb} (~3.2k LoC with
seldon — seldon's routing lives natively in serving/router.py) and
kubeflow/{spark,pachyderm,weaveflux,knative-build}. These are deployable
catalog entries in the reference sense: prototype + params → manifests.
Where the reference deployed a GPU inference server, the TPU catalog
points the same slot at the TPU model server (serving/)."""

from __future__ import annotations

from ..api import k8s
from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"


@register("openvino", "OpenVINO model server for CPU-only inference pools "
                      "(kubeflow/openvino parity)")
def openvino(namespace: str = "kubeflow",
             model_path: str = "gs://models/resnet",
             batch_size: int = 1,
             replicas: int = 1) -> list[dict]:
    dep = H.deployment(
        "openvino-model-server", namespace,
        "intelaipg/openvino-model-server:0.2",
        args=["/ie-serving-py/start_server.sh", "ie_serving", "model",
              f"--model_path={model_path}", "--model_name=default",
              f"--batch_size={batch_size}", "--port=80"],
        replicas=replicas, port=80)
    svc = H.service("openvino-model-server", namespace, 80)
    return [dep, svc]


@register("tpu-inference-server", "Multi-model TPU inference server — the "
                                  "nvidia-inference-server (TensorRT) slot "
                                  "served by the TPU data plane")
def tpu_inference_server(namespace: str = "kubeflow",
                         model_repository: str = "gs://models",
                         replicas: int = 1) -> list[dict]:
    """The reference deploys TensorRT Inference Server with a model
    repository param (kubeflow/nvidia-inference-server); the TPU catalog
    fills that slot with our model server (serving/model_server.py) which
    loads every model under the repository root."""
    dep = H.deployment(
        "tpu-inference-server", namespace, f"{IMG}/tpu-serving:{VERSION}",
        args=[f"--model-repository={model_repository}", "--port=8500",
              "--grpc-port=9000"],
        replicas=replicas, port=8500)
    dep["spec"]["template"]["spec"]["nodeSelector"] = {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5e"}
    svc = H.service("tpu-inference-server", namespace, 8500)
    grpc = H.service("tpu-inference-server-grpc", namespace, 9000)
    grpc["spec"]["selector"] = {H.APP_LABEL: "tpu-inference-server"}
    return [dep, svc, grpc]


@register("modeldb", "Model registry: modeldb backend + frontend + mongo "
                     "(kubeflow/modeldb parity)")
def modeldb(namespace: str = "kubeflow") -> list[dict]:
    mongo = H.deployment("modeldb-db", namespace, "mongo:3.4",
                         port=27017)
    mongo_svc = H.service("modeldb-db", namespace, 27017)
    backend = H.deployment("modeldb-backend", namespace,
                           "mitdbg/modeldb-backend:latest",
                           args=["modeldb-db", "27017"], port=6543)
    backend_svc = H.service("modeldb-backend", namespace, 6543)
    front = H.deployment("modeldb-frontend", namespace,
                         "mitdbg/modeldb-frontend:latest",
                         args=["modeldb-backend"], port=3000)
    front_svc = H.service("modeldb-frontend", namespace, 3000)
    return [mongo, mongo_svc, backend, backend_svc, front, front_svc]


@register("spark-operator", "Spark operator + SparkApplication CRD "
                            "(kubeflow/spark parity)")
def spark_operator(namespace: str = "kubeflow",
                   spark_version: str = "v2.4.0") -> list[dict]:
    crd = H.crd("sparkapplications", "SparkApplication",
                "sparkoperator.k8s.io", ["v1beta1"])
    sched_crd = H.crd("scheduledsparkapplications",
                      "ScheduledSparkApplication",
                      "sparkoperator.k8s.io", ["v1beta1"])
    sa = H.service_account("sparkoperator", namespace)
    role = H.cluster_role("sparkoperator", [
        {"apiGroups": ["sparkoperator.k8s.io"], "resources": ["*"],
         "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["pods", "services",
                                          "configmaps"],
         "verbs": ["*"]},
    ])
    binding = H.cluster_role_binding("sparkoperator", "sparkoperator",
                                     "sparkoperator", namespace)
    dep = H.deployment(
        "sparkoperator", namespace,
        f"gcr.io/spark-operator/spark-operator:{spark_version}",
        args=["-logtostderr", "-enable-metrics=true"],
        service_account="sparkoperator", port=10254)
    return [crd, sched_crd, sa, role, binding, dep]


@register("pachyderm", "Versioned data pipelines: pachd + etcd "
                       "(kubeflow/pachyderm parity)")
def pachyderm(namespace: str = "kubeflow",
              storage_capacity: str = "10Gi") -> list[dict]:
    etcd = H.deployment("pachyderm-etcd", namespace,
                        "quay.io/coreos/etcd:v3.3.5",
                        args=["etcd", "--listen-client-urls=http://0.0.0.0:2379",
                              "--advertise-client-urls=http://0.0.0.0:2379"],
                        port=2379)
    etcd_svc = H.service("pachyderm-etcd", namespace, 2379)
    sa = H.service_account("pachyderm", namespace)
    pachd = H.deployment("pachd", namespace, "pachyderm/pachd:1.7.0",
                         env={"PACH_ROOT": "/pach",
                              "ETCD_SERVICE_HOST": "pachyderm-etcd",
                              "ETCD_SERVICE_PORT": "2379",
                              "PACHD_VERSION": "1.7.0"},
                         service_account="pachyderm", port=650)
    pachd_svc = H.service("pachd", namespace, 650)
    pvc = {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "pach-disk", "namespace": namespace},
        "spec": {"accessModes": ["ReadWriteOnce"],
                 "resources": {"requests": {"storage": storage_capacity}}},
    }
    return [etcd, etcd_svc, sa, pachd, pachd_svc, pvc]


@register("weaveflux", "GitOps sync: flux + memcached "
                       "(kubeflow/weaveflux parity)")
def weaveflux(namespace: str = "kubeflow",
              git_url: str = "") -> list[dict]:
    sa = H.service_account("flux", namespace)
    role = H.cluster_role("flux", [
        {"apiGroups": ["*"], "resources": ["*"], "verbs": ["*"]},
    ])
    binding = H.cluster_role_binding("flux", "flux", "flux", namespace)
    flux = H.deployment(
        "flux", namespace, "quay.io/weaveworks/flux:1.4.2",
        args=([f"--git-url={git_url}"] if git_url else []) +
        ["--memcached-hostname=flux-memcached"],
        service_account="flux", port=3030)
    memcached = H.deployment("flux-memcached", namespace,
                             "memcached:1.4.25", args=["-m", "64"],
                             port=11211)
    mc_svc = H.service("flux-memcached", namespace, 11211)
    return [sa, role, binding, flux, memcached, mc_svc]


@register("knative-build", "Build CRD + controller/webhook "
                           "(kubeflow/knative-build parity)")
def knative_build(namespace: str = "knative-build") -> list[dict]:
    ns = k8s.make("v1", "Namespace", namespace)
    crds = [
        H.crd("builds", "Build", "build.knative.dev", ["v1alpha1"]),
        H.crd("buildtemplates", "BuildTemplate", "build.knative.dev",
              ["v1alpha1"]),
    ]
    sa = H.service_account("build-controller", namespace)
    role = H.cluster_role("knative-build-admin", [
        {"apiGroups": ["build.knative.dev"], "resources": ["*"],
         "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["pods", "secrets", "events"],
         "verbs": ["*"]},
    ])
    binding = H.cluster_role_binding("build-controller-admin",
                                     "knative-build-admin",
                                     "build-controller", namespace)
    controller = H.deployment(
        "build-controller", namespace,
        "gcr.io/build-crd/github.com/knative/build/cmd/controller",
        service_account="build-controller", port=9090)
    webhook = H.deployment(
        "build-webhook", namespace,
        "gcr.io/build-crd/github.com/knative/build/cmd/webhook",
        service_account="build-controller", port=8443)
    return [ns, *crds, sa, role, binding, controller, webhook]
