"""Kubebench package: benchmark harness operator.

Reference: kubeflow/kubebench (kubebench-operator.libsonnet:10-27 CRD +
operator; kubebench-job.libsonnet:6-30,53,100-120 the Argo workflow per
benchmark: configurator → launch job → reporter, PVC roots, KUBEBENCH_* env
contract; kubebench-dashboard.libsonnet).
"""

from __future__ import annotations

from ..api import k8s
from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"

# The env contract injected into benchmark steps (kubebench-job.libsonnet
# KUBEBENCH_* vars) — preserved verbatim for workload compatibility.
KUBEBENCH_ENV = ("KUBEBENCH_CONFIG_ROOT", "KUBEBENCH_DATA_ROOT",
                 "KUBEBENCH_EXP_ROOT", "KUBEBENCH_EXP_ID")


@register("kubebench", "Benchmark harness: KubebenchJob CRD + operator + "
                       "dashboard (kubeflow/kubebench parity)")
def kubebench(namespace: str = "kubeflow",
              config_pvc: str = "kubebench-config",
              data_pvc: str = "kubebench-data",
              experiments_pvc: str = "kubebench-exp") -> list[dict]:
    kb_crd = H.crd("kubebenchjobs", "KubebenchJob", "kubeflow.org",
                   ["v1alpha1"], schema={
                       "type": "object",
                       "properties": {"spec": {
                           "type": "object",
                           "properties": {
                               "jobSpec": {"type": "object"},
                               "reporterType": {"type": "string"},
                               "configRoot": {"type": "string"},
                               "dataRoot": {"type": "string"},
                               "experimentsRoot": {"type": "string"},
                           }}}})
    sa = H.service_account("kubebench-operator", namespace)
    role = H.cluster_role("kubebench-operator", [
        {"apiGroups": ["kubeflow.org", "tpu.kubeflow.org"],
         "resources": ["*"], "verbs": ["*"]},
        {"apiGroups": ["batch"], "resources": ["jobs"], "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["pods", "configmaps",
                                          "persistentvolumeclaims"],
         "verbs": ["*"]},
    ])
    binding = H.cluster_role_binding("kubebench-operator",
                                     "kubebench-operator",
                                     "kubebench-operator", namespace)
    dep = H.deployment("kubebench-operator", namespace,
                       f"{IMG}/kubebench-operator:{VERSION}",
                       service_account="kubebench-operator")
    pvcs = []
    for pvc_name in (config_pvc, data_pvc, experiments_pvc):
        pvc = k8s.make("v1", "PersistentVolumeClaim", pvc_name, namespace)
        pvc["spec"] = {"accessModes": ["ReadWriteMany"],
                       "resources": {"requests": {"storage": "10Gi"}}}
        pvcs.append(pvc)
    dash = H.deployment("kubebench-dashboard", namespace,
                        f"{IMG}/kubebench-dashboard:{VERSION}", port=9303)
    dash_svc = H.service("kubebench-dashboard", namespace, 80,
                         target_port=9303)
    dash_vs = H.virtual_service("kubebench-dashboard", namespace,
                                "/kubebench/", "kubebench-dashboard", 80)
    return [kb_crd, sa, role, binding, dep, *pvcs, dash, dash_svc, dash_vs]
