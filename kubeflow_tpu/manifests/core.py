"""Core platform packages: ingress, metacontroller, application, dashboard.

Reference packages: kubeflow/common (ambassador, centraldashboard,
spartakus, echo-server), kubeflow/metacontroller, kubeflow/application,
dependencies/istio.
"""

from __future__ import annotations

from ..api import k8s
from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
# per-image pin the auto-update bot retags independently (image_update.py)
CENTRALDASHBOARD_VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"


@register("istio", "Istio gateway + kubeflow routing (dependencies/istio analog)")
def istio(namespace: str = "kubeflow") -> list[dict]:
    gw = k8s.make("networking.istio.io/v1alpha3", "Gateway",
                  "kubeflow-gateway", namespace)
    gw["spec"] = {
        "selector": {"istio": "ingressgateway"},
        "servers": [{"hosts": ["*"],
                     "port": {"name": "http", "number": 80,
                              "protocol": "HTTP"}}],
    }
    return [gw]


@register("ambassador", "Ambassador API gateway (kubeflow/common/ambassador.libsonnet)")
def ambassador(namespace: str = "kubeflow", replicas: int = 3) -> list[dict]:
    sa = H.service_account("ambassador", namespace)
    role = H.cluster_role("ambassador", [
        {"apiGroups": [""], "resources": ["services", "configmaps", "secrets"],
         "verbs": ["get", "list", "watch"]},
    ])
    binding = H.cluster_role_binding("ambassador", "ambassador", "ambassador",
                                     namespace)
    dep = H.deployment("ambassador", namespace,
                       f"{IMG}/ambassador:{VERSION}", replicas=replicas,
                       port=80, service_account="ambassador")
    svc = H.service("ambassador", namespace, 80)
    return [sa, role, binding, dep, svc]


@register("metacontroller", "Lambda-controller engine (kubeflow/metacontroller)")
def metacontroller(namespace: str = "kubeflow") -> list[dict]:
    crd_comp = H.crd("compositecontrollers", "CompositeController",
                     "metacontroller.k8s.io", ["v1alpha1"], scope="Cluster")
    crd_deco = H.crd("decoratorcontrollers", "DecoratorController",
                     "metacontroller.k8s.io", ["v1alpha1"], scope="Cluster")
    sa = H.service_account("metacontroller", namespace)
    binding = H.cluster_role_binding("metacontroller", "cluster-admin",
                                     "metacontroller", namespace)
    sts = k8s.make("apps/v1", "StatefulSet", "metacontroller", namespace,
                   labels=H.std_labels("metacontroller"))
    sts["spec"] = {
        "replicas": 1,
        "serviceName": "metacontroller",
        "selector": {"matchLabels": {H.APP_LABEL: "metacontroller"}},
        "template": {
            "metadata": {"labels": H.std_labels("metacontroller")},
            "spec": {"serviceAccountName": "metacontroller",
                     "containers": [{"name": "metacontroller",
                                     "image": f"{IMG}/metacontroller:{VERSION}"}]},
        },
    }
    return [crd_comp, crd_deco, sa, binding, sts]


@register("application", "Application CRD aggregating component resources "
                         "(kubeflow/application/application.libsonnet)")
def application(namespace: str = "kubeflow") -> list[dict]:
    app_crd = H.crd("applications", "Application", "app.k8s.io", ["v1beta1"])
    sync_cm = H.config_map("application-sync-hook", namespace, {
        "sync": "builtin:application-controller",
    })
    composite = k8s.make("metacontroller.k8s.io/v1alpha1",
                         "CompositeController", "application-controller")
    composite["spec"] = {
        "generateSelector": True,
        "parentResource": {"apiVersion": "app.k8s.io/v1beta1",
                           "resource": "applications"},
        "hooks": {"sync": {"configMapRef": {"name": "application-sync-hook",
                                            "namespace": namespace}}},
    }
    return [app_crd, sync_cm, composite]


@register("centraldashboard", "Central dashboard UI + API "
                              "(components/centraldashboard)")
def centraldashboard(namespace: str = "kubeflow") -> list[dict]:
    sa = H.service_account("centraldashboard", namespace)
    role = H.cluster_role("centraldashboard", [
        {"apiGroups": [""], "resources": ["events", "namespaces", "nodes",
                                          "pods"],
         "verbs": ["get", "list", "watch"]},
    ])
    binding = H.cluster_role_binding("centraldashboard", "centraldashboard",
                                     "centraldashboard", namespace)
    dep = H.deployment("centraldashboard", namespace,
                       f"{IMG}/centraldashboard:{CENTRALDASHBOARD_VERSION}", port=8082,
                       service_account="centraldashboard")
    svc = H.service("centraldashboard", namespace, 80, target_port=8082)
    vs = H.virtual_service("centraldashboard", namespace, "/", "centraldashboard", 80)
    return [sa, role, binding, dep, svc, vs]


@register("spartakus", "Usage telemetry reporter (kubeflow/common/spartakus.libsonnet)")
def spartakus(namespace: str = "kubeflow", usage_id: int = 0,
              report_interval_s: int = 86400) -> list[dict]:
    dep = H.deployment(
        "spartakus-volunteer", namespace, f"{IMG}/spartakus:{VERSION}",
        args=["volunteer", f"--cluster-id={usage_id}",
              f"--period={report_interval_s}s"])
    return [dep]


@register("echo-server", "Minimal HTTP echo app (CI routing target, "
                         "components/echo-server)")
def echo_server(namespace: str = "kubeflow") -> list[dict]:
    dep = H.deployment("echo-server", namespace, f"{IMG}/echo-server:{VERSION}",
                       port=8080)
    svc = H.service("echo-server", namespace, 80, target_port=8080)
    return [dep, svc]


@register("gatekeeper", "Basic-auth gate + login UI (components/gatekeeper, "
                        "components/kflogin)")
def gatekeeper(namespace: str = "kubeflow", username: str = "admin") -> list[dict]:
    secret = k8s.make("v1", "Secret", "kubeflow-login", namespace)
    secret["stringData"] = {"username": username, "passwordhash": ""}
    dep = H.deployment("gatekeeper", namespace, f"{IMG}/gatekeeper:{VERSION}",
                       port=8085, env={"USERNAME_SECRET": "kubeflow-login"})
    svc = H.service("gatekeeper", namespace, 8085)
    login = H.deployment("kflogin", namespace, f"{IMG}/kflogin:{VERSION}",
                         port=5000)
    login_svc = H.service("kflogin", namespace, 80, target_port=5000)
    vs = H.virtual_service("kflogin", namespace, "/kflogin", "kflogin", 80)
    return [secret, dep, svc, login, login_svc, vs]


@register("bootstrapper", "In-cluster bootstrap StatefulSet — the "
                          "one-command install (bootstrap/bootstrapper.yaml "
                          "parity)")
def bootstrapper(namespace: str = "kubeflow-admin",
                 apps_root: str = "/opt/bootstrap/apps") -> list[dict]:
    ns = k8s.make("v1", "Namespace", namespace)
    sa = H.service_account("kubeflow-bootstrapper", namespace)
    binding = H.cluster_role_binding("kubeflow-cluster-admin",
                                     "cluster-admin",
                                     "kubeflow-bootstrapper", namespace)
    sts = {
        "apiVersion": "apps/v1", "kind": "StatefulSet",
        "metadata": {"name": "kubeflow-bootstrapper",
                     "namespace": namespace,
                     "labels": H.std_labels("kubeflow-bootstrapper")},
        "spec": {
            "serviceName": "kubeflow-bootstrapper",
            "replicas": 1,
            "selector": {"matchLabels":
                         {H.APP_LABEL: "kubeflow-bootstrapper"}},
            "template": {
                "metadata": {"labels":
                             {H.APP_LABEL: "kubeflow-bootstrapper"}},
                "spec": {
                    "serviceAccountName": "kubeflow-bootstrapper",
                    "containers": [{
                        "name": "bootstrapper",
                        "image": f"{IMG}/bootstrapper:{VERSION}",
                        "args": ["serve-bootstrap",
                                 f"--apps-root={apps_root}",
                                 "--host=0.0.0.0", "--port=8085"],
                        "ports": [{"containerPort": 8085}],
                        "volumeMounts": [{"name": "apps",
                                          "mountPath": apps_root}],
                    }],
                },
            },
            "volumeClaimTemplates": [{
                "metadata": {"name": "apps"},
                "spec": {"accessModes": ["ReadWriteOnce"],
                         "resources": {"requests": {"storage": "10Gi"}}},
            }],
        },
    }
    svc = H.service("kubeflow-bootstrapper", namespace, 8085)
    return [ns, sa, binding, sts, svc]
