"""Component registry: name → builder(params) → [manifests].

The prototype+params surface of the reference's ksonnet registry
(kubeflow/<pkg>/prototypes/*.jsonnet with @optionalParam headers), kept so
KfDef.components / componentParams drive generation the same way
`ks generate <prototype> --param` did.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

Builder = Callable[..., list[dict]]


@dataclass
class Component:
    name: str
    builder: Builder
    description: str = ""
    # param name -> default (introspected from the builder signature)
    params: dict[str, Any] = field(default_factory=dict)


REGISTRY: dict[str, Component] = {}


def register(name: str, description: str = "") -> Callable[[Builder], Builder]:
    def deco(fn: Builder) -> Builder:
        sig = inspect.signature(fn)
        params = {
            p.name: (p.default if p.default is not inspect.Parameter.empty
                     else None)
            for p in sig.parameters.values()
        }
        REGISTRY[name] = Component(name=name, builder=fn,
                                   description=description, params=params)
        return fn

    return deco


def component_names() -> list[str]:
    return sorted(REGISTRY)


def build_component(name: str, params: Optional[dict] = None) -> list[dict]:
    if name not in REGISTRY:
        raise KeyError(
            f"unknown component {name!r}; known: {component_names()}")
    comp = REGISTRY[name]
    params = params or {}
    sig = inspect.signature(comp.builder)
    unknown = set(params) - set(sig.parameters)
    if unknown:
        raise ValueError(
            f"component {name}: unknown params {sorted(unknown)}; "
            f"valid: {sorted(sig.parameters)}")
    return comp.builder(**params)
