"""Multi-tenancy packages: profiles + admission webhook.

Reference: kubeflow/profiles (Profile/Permission CRDs, sync-profile.jsonnet),
components/profile-controller, components/admission-webhook (PodDefault),
components/access-management swagger (SURVEY §2.6).
"""

from __future__ import annotations

from ..api import k8s
from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"


@register("profiles", "Profile CRD + multi-tenancy controller "
                      "(components/profile-controller parity)")
def profiles(namespace: str = "kubeflow") -> list[dict]:
    profile_crd = H.crd("profiles", "Profile", "kubeflow.org", ["v1alpha1"],
                        scope="Cluster", schema={
                            "type": "object",
                            "properties": {"spec": {
                                "type": "object",
                                "properties": {
                                    "owner": {"type": "object"},
                                    "resourceQuotaSpec": {"type": "object"},
                                }}}})
    permission_crd = H.crd("permissions", "Permission", "kubeflow.org",
                           ["v1alpha1"])
    sa = H.service_account("profile-controller", namespace)
    binding = H.cluster_role_binding("profile-controller", "cluster-admin",
                                     "profile-controller", namespace)
    dep = H.deployment("profile-controller", namespace,
                       f"{IMG}/profile-controller:{VERSION}",
                       service_account="profile-controller")
    return [profile_crd, permission_crd, sa, binding, dep]


@register("admission-webhook", "PodDefault mutating webhook "
                               "(components/admission-webhook parity)")
def admission_webhook(namespace: str = "kubeflow") -> list[dict]:
    pd_crd = H.crd("poddefaults", "PodDefault", "kubeflow.org", ["v1alpha1"],
                   schema={
                       "type": "object",
                       "properties": {"spec": {
                           "type": "object",
                           "properties": {
                               "selector": {"type": "object"},
                               "env": {"type": "array"},
                               "volumes": {"type": "array"},
                               "volumeMounts": {"type": "array"},
                           }}}})
    sa = H.service_account("admission-webhook", namespace)
    role = H.cluster_role("admission-webhook", [
        {"apiGroups": ["kubeflow.org"], "resources": ["poddefaults"],
         "verbs": ["get", "list", "watch"]},
    ])
    binding = H.cluster_role_binding("admission-webhook", "admission-webhook",
                                     "admission-webhook", namespace)
    dep = H.deployment("admission-webhook", namespace,
                       f"{IMG}/admission-webhook:{VERSION}", port=4443,
                       service_account="admission-webhook")
    svc = H.service("admission-webhook", namespace, 443, target_port=4443)
    webhook = k8s.make("admissionregistration.k8s.io/v1",
                       "MutatingWebhookConfiguration", "admission-webhook")
    webhook["webhooks"] = [{
        "name": "admission-webhook.kubeflow.org",
        "clientConfig": {"service": {"name": "admission-webhook",
                                     "namespace": namespace,
                                     "path": "/apply-poddefault"}},
        "rules": [{"apiGroups": [""], "apiVersions": ["v1"],
                   "operations": ["CREATE"], "resources": ["pods"]}],
        "admissionReviewVersions": ["v1"],
        "sideEffects": "None",
    }]
    return [pd_crd, sa, role, binding, dep, svc, webhook]


@register("credentials-pod-preset", "Cloud-credential PodDefault "
                                    "(kubeflow/credentials-pod-preset parity)")
def credentials_pod_preset(namespace: str = "kubeflow",
                           secret_name: str = "user-cloud-creds") -> list[dict]:
    pd = k8s.make("kubeflow.org/v1alpha1", "PodDefault", "cloud-credentials",
                  namespace)
    pd["spec"] = {
        "selector": {"matchLabels": {"inject-cloud-creds": "true"}},
        "env": [{"name": "GOOGLE_APPLICATION_CREDENTIALS",
                 "value": "/secret/creds.json"}],
        "volumes": [{"name": "creds",
                     "secret": {"secretName": secret_name}}],
        "volumeMounts": [{"name": "creds", "mountPath": "/secret",
                          "readOnly": True}],
    }
    return [pd]


@register("access-management", "KFAM Profile/Binding grant API "
                               "(components/access-management swagger, "
                               "served by webapps/access_management.py)")
def access_management(namespace: str = "kubeflow") -> list[dict]:
    sa = H.service_account("kfam", namespace)
    role = H.cluster_role("kfam", [
        {"apiGroups": ["kubeflow.org"], "resources": ["profiles"],
         "verbs": ["get", "list", "create", "delete"]},
        {"apiGroups": ["rbac.authorization.k8s.io"],
         "resources": ["rolebindings"],
         "verbs": ["get", "list", "create", "update", "delete"]},
    ])
    binding = H.cluster_role_binding("kfam", "kfam", "kfam", namespace)
    dep = H.deployment("profiles-kfam", namespace,
                       f"{IMG}/kfam:{VERSION}", port=8081,
                       service_account="kfam")
    svc = H.service("profiles-kfam", namespace, 8081)
    return [sa, role, binding, dep, svc]
