"""The manifest registry: programmatic builders replacing ksonnet.

The reference's deployable catalog is 33 jsonnet packages
(SURVEY.md §2.3, kubeflow/ dir): prototypes with @param headers expanded by
`ks generate`. Here each package is a typed Python builder
``build(params) -> [manifests]`` registered by name, keeping the same
surface (component name + params in KfDef.componentParams) with golden
tests instead of jsonnet test harnesses.
"""

from .registry import REGISTRY, build_component, component_names, register

__all__ = ["REGISTRY", "register", "build_component", "component_names"]

# Importing the package modules populates the registry.
from . import (core, training, serving, notebooks, multitenancy, katib,  # noqa: F401,E402
               kubebench, observability, cloud_aws, cloud_gcp, ecosystem,
               pipelines)
