"""The pipelines package: argo engine + Kubeflow Pipelines services.

Reference: kubeflow/argo/argo.libsonnet (Workflow CRD + controller + UI)
and kubeflow/pipeline/*.libsonnet (apiserver, scheduledworkflow,
persistenceagent, ui, mysql/minio storage — 1,832 LoC of jsonnet). The
TPU build's runtimes live in kubeflow_tpu/workflows (engine) and
kubeflow_tpu/pipelines (scheduled/store/api_server); these manifests
deploy them.
"""

from __future__ import annotations

from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"


@register("argo", "Workflow CRD + engine controller "
                  "(kubeflow/argo/argo.libsonnet parity)")
def argo(namespace: str = "kubeflow") -> list[dict]:
    crd = H.crd("workflows", "Workflow", "argoproj.io", ["v1alpha1"])
    sa = H.service_account("workflow-controller", namespace)
    role = H.cluster_role("workflow-controller", [
        {"apiGroups": ["argoproj.io"], "resources": ["workflows"],
         "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["pods", "configmaps"],
         "verbs": ["*"]},
        {"apiGroups": ["tpu.kubeflow.org", "kubeflow.org"],
         "resources": ["*"], "verbs": ["*"]},  # resource templates
    ])
    binding = H.cluster_role_binding("workflow-controller",
                                     "workflow-controller",
                                     "workflow-controller", namespace)
    dep = H.deployment("workflow-controller", namespace,
                       f"{IMG}/manager:{VERSION}",
                       args=["--controllers=workflow"],
                       service_account="workflow-controller", port=9090)
    return [crd, sa, role, binding, dep]


@register("pipeline-scheduledworkflow",
          "ScheduledWorkflow CRD + cron controller "
          "(pipeline-scheduledworkflow.libsonnet parity)")
def pipeline_scheduledworkflow(namespace: str = "kubeflow") -> list[dict]:
    crd = H.crd("scheduledworkflows", "ScheduledWorkflow", "kubeflow.org",
                ["v1beta1"])
    dep = H.deployment("ml-pipeline-scheduledworkflow", namespace,
                       f"{IMG}/manager:{VERSION}",
                       args=["--controllers=scheduledworkflow"],
                       service_account="workflow-controller", port=9091)
    return [crd, dep]


@register("pipeline-apiserver", "Pipeline run/job REST API + persistence "
                                "(pipeline-apiserver + "
                                "persistenceagent + mysql parity)")
def pipeline_apiserver(namespace: str = "kubeflow",
                       store_path: str = "/var/lib/kubeflow/runs.db"
                       ) -> list[dict]:
    dep = H.deployment(
        "ml-pipeline", namespace, f"{IMG}/pipeline-api:{VERSION}",
        args=[f"--store={store_path}"],
        service_account="workflow-controller", port=8888)
    svc = H.service("ml-pipeline", namespace, 8888)
    # persistence agent: workflow watcher feeding the run store (the
    # sqlite file replaces the reference's mysql.libsonnet pod)
    agent = H.deployment(
        "ml-pipeline-persistenceagent", namespace,
        f"{IMG}/manager:{VERSION}",
        args=["--controllers=persistenceagent", f"--store={store_path}"],
        service_account="workflow-controller", port=9092)
    return [dep, svc, agent]


@register("pipeline-ui", "Pipelines UI page served by the central "
                         "dashboard (pipeline-ui.libsonnet parity)")
def pipeline_ui(namespace: str = "kubeflow") -> list[dict]:
    svc = H.service("ml-pipeline-ui", namespace, 3000)
    vs = H.virtual_service("ml-pipeline-ui", namespace, "/pipeline/",
                           "ml-pipeline-ui", 3000)
    return [svc, vs]
