"""The pipelines package: argo engine + Kubeflow Pipelines services.

Reference: kubeflow/argo/argo.libsonnet (Workflow CRD + controller + UI)
and kubeflow/pipeline/*.libsonnet (apiserver, scheduledworkflow,
persistenceagent, ui, mysql/minio storage — 1,832 LoC of jsonnet). The
TPU build's runtimes live in kubeflow_tpu/workflows (engine) and
kubeflow_tpu/pipelines (scheduled/store/api_server); these manifests
deploy them.
"""

from __future__ import annotations

from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"


@register("argo", "Workflow CRD + engine controller "
                  "(kubeflow/argo/argo.libsonnet parity)")
def argo(namespace: str = "kubeflow") -> list[dict]:
    crd = H.crd("workflows", "Workflow", "argoproj.io", ["v1alpha1"])
    sa = H.service_account("workflow-controller", namespace)
    role = H.cluster_role("workflow-controller", [
        {"apiGroups": ["argoproj.io"], "resources": ["workflows"],
         "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["pods", "configmaps"],
         "verbs": ["*"]},
        {"apiGroups": ["tpu.kubeflow.org", "kubeflow.org"],
         "resources": ["*"], "verbs": ["*"]},  # resource templates
    ])
    binding = H.cluster_role_binding("workflow-controller",
                                     "workflow-controller",
                                     "workflow-controller", namespace)
    dep = H.deployment("workflow-controller", namespace,
                       f"{IMG}/manager:{VERSION}",
                       args=["--controllers=workflow"],
                       service_account="workflow-controller", port=9090)
    return [crd, sa, role, binding, dep]


@register("pipeline-scheduledworkflow",
          "ScheduledWorkflow CRD + cron controller "
          "(pipeline-scheduledworkflow.libsonnet parity)")
def pipeline_scheduledworkflow(namespace: str = "kubeflow") -> list[dict]:
    crd = H.crd("scheduledworkflows", "ScheduledWorkflow", "kubeflow.org",
                ["v1beta1"])
    dep = H.deployment("ml-pipeline-scheduledworkflow", namespace,
                       f"{IMG}/manager:{VERSION}",
                       args=["--controllers=scheduledworkflow"],
                       service_account="workflow-controller", port=9091)
    return [crd, dep]


def _mount_store(dep: dict, pvc: str, mount_path: str) -> dict:
    pod = dep["spec"]["template"]["spec"]
    pod["volumes"] = [{"name": "store",
                       "persistentVolumeClaim": {"claimName": pvc}}]
    pod["containers"][0]["volumeMounts"] = [
        {"name": "store", "mountPath": mount_path}]
    return dep


@register("pipeline-apiserver", "Pipeline run/job REST API + persistence "
                                "(pipeline-apiserver + "
                                "persistenceagent parity)")
def pipeline_apiserver(namespace: str = "kubeflow",
                       store_path: str = "/var/lib/kubeflow/runs.db"
                       ) -> list[dict]:
    import os
    mount = os.path.dirname(store_path) or "/var/lib/kubeflow"
    dep = _mount_store(H.deployment(
        "ml-pipeline", namespace, f"{IMG}/pipeline-api:{VERSION}",
        args=[f"--store={store_path}"],
        service_account="workflow-controller", port=8888),
        "ml-pipeline-db", mount)
    svc = H.service("ml-pipeline", namespace, 8888)
    # persistence agent rides the SAME pod as a second container: the
    # store is a PVC-backed sqlite file, so both writers must share a
    # node (ReadWriteOnce) — co-containering is the reference's
    # mysql-colocated shape translated to the embedded DB
    dep["spec"]["template"]["spec"]["containers"].append({
        "name": "persistenceagent",
        "image": f"{IMG}/manager:{VERSION}",
        "args": ["--controllers=persistenceagent", f"--store={store_path}"],
        "ports": [{"containerPort": 9092}],
        "volumeMounts": [{"name": "store", "mountPath": mount}],
    })
    return [dep, svc]


@register("pipeline-db", "Durable run-store volume — the mysql.libsonnet "
                         "slot (PVC-backed sqlite replaces the MySQL pod)")
def pipeline_db(namespace: str = "kubeflow",
                capacity: str = "20Gi",
                storage_class: str = "") -> list[dict]:
    pvc = {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "ml-pipeline-db", "namespace": namespace,
                     "labels": H.std_labels("ml-pipeline-db")},
        "spec": {
            "accessModes": ["ReadWriteOnce"],
            "resources": {"requests": {"storage": capacity}},
            **({"storageClassName": storage_class} if storage_class else {}),
        },
    }
    return [pvc]


@register("minio", "S3-compatible artifact store "
                   "(kubeflow/pipeline/minio.libsonnet parity)")
def minio(namespace: str = "kubeflow",
          capacity: str = "20Gi",
          access_key: str = "minio",
          secret_key: str = "minio123") -> list[dict]:
    pvc = {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "minio-pvc", "namespace": namespace},
        "spec": {"accessModes": ["ReadWriteOnce"],
                 "resources": {"requests": {"storage": capacity}}},
    }
    secret = {
        "apiVersion": "v1", "kind": "Secret",
        "metadata": {"name": "mlpipeline-minio-artifact",
                     "namespace": namespace},
        "stringData": {"accesskey": access_key, "secretkey": secret_key},
    }
    dep = _mount_store(H.deployment(
        "minio", namespace, "minio/minio:RELEASE.2019-02-26T19-51-55Z",
        args=["server", "/data"],
        env={"MINIO_ACCESS_KEY": access_key,
             "MINIO_SECRET_KEY": secret_key},
        port=9000), "minio-pvc", "/data")
    svc = H.service("minio-service", namespace, 9000)
    svc["spec"]["selector"] = {H.APP_LABEL: "minio"}
    return [pvc, secret, dep, svc]


@register("pipeline-viewercrd", "Viewer CRD + controller for run artifact "
                                "viewers (pipeline-viewercrd.libsonnet "
                                "parity)")
def pipeline_viewercrd(namespace: str = "kubeflow",
                       max_num_viewers: int = 50) -> list[dict]:
    crd = H.crd("viewers", "Viewer", "kubeflow.org", ["v1beta1"])
    sa = H.service_account("ml-pipeline-viewer-crd-sa", namespace)
    role = H.cluster_role("ml-pipeline-viewer-controller", [
        {"apiGroups": ["kubeflow.org"], "resources": ["viewers"],
         "verbs": ["*"]},
        {"apiGroups": ["apps"], "resources": ["deployments"],
         "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["services"], "verbs": ["*"]},
    ])
    binding = H.cluster_role_binding("ml-pipeline-viewer-controller",
                                     "ml-pipeline-viewer-controller",
                                     "ml-pipeline-viewer-crd-sa", namespace)
    dep = H.deployment(
        "ml-pipeline-viewer-controller", namespace,
        f"{IMG}/viewer-crd-controller:{VERSION}",
        args=[f"--max_num_viewers={max_num_viewers}"],
        service_account="ml-pipeline-viewer-crd-sa", port=9093)
    return [crd, sa, role, binding, dep]


@register("pipeline-ui", "Pipelines UI page served by the central "
                         "dashboard (pipeline-ui.libsonnet parity)")
def pipeline_ui(namespace: str = "kubeflow") -> list[dict]:
    svc = H.service("ml-pipeline-ui", namespace, 3000)
    vs = H.virtual_service("ml-pipeline-ui", namespace, "/pipeline/",
                           "ml-pipeline-ui", 3000)
    return [svc, vs]
