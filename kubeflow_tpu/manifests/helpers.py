"""Shared manifest constructors (the util.libsonnet / common idioms)."""

from __future__ import annotations

from typing import Optional, Sequence

from ..api import k8s

APP_LABEL = "app.kubernetes.io/name"
PART_OF = "app.kubernetes.io/part-of"


def std_labels(name: str) -> dict:
    return {APP_LABEL: name, PART_OF: "kubeflow"}


def deployment(name: str, namespace: str, image: str, *,
               args: Optional[list] = None, env: Optional[dict] = None,
               port: Optional[int] = None, replicas: int = 1,
               service_account: Optional[str] = None,
               resources: Optional[dict] = None,
               labels: Optional[dict] = None,
               pod_annotations: Optional[dict] = None) -> dict:
    lbl = {**std_labels(name), **(labels or {})}
    container: dict = {"name": name, "image": image}
    if args:
        container["args"] = list(args)
    if env:
        container["env"] = [{"name": k, "value": str(v)} for k, v in env.items()]
    if port:
        container["ports"] = [{"containerPort": port}]
    if resources:
        container["resources"] = resources
    template_meta: dict = {"labels": lbl}
    if pod_annotations:
        # pod-template annotations (prometheus.io/scrape et al. —
        # annotation-based discovery reads the POD, not the Deployment)
        template_meta["annotations"] = dict(pod_annotations)
    spec: dict = {
        "replicas": replicas,
        "selector": {"matchLabels": {APP_LABEL: name}},
        "template": {
            "metadata": template_meta,
            "spec": {"containers": [container]},
        },
    }
    if service_account:
        spec["template"]["spec"]["serviceAccountName"] = service_account
    return k8s.make("apps/v1", "Deployment", name, namespace, labels=lbl,
                    spec=spec)


def service(name: str, namespace: str, port: int, target_port: Optional[int] = None,
            selector_name: Optional[str] = None, headless: bool = False) -> dict:
    spec: dict = {
        "selector": {APP_LABEL: selector_name or name},
        "ports": [{"port": port, "targetPort": target_port or port,
                   "name": "http"}],
    }
    if headless:
        spec["clusterIP"] = "None"
    return k8s.make("v1", "Service", name, namespace,
                    labels=std_labels(name), spec=spec)


def service_account(name: str, namespace: str) -> dict:
    return k8s.make("v1", "ServiceAccount", name, namespace,
                    labels=std_labels(name))


def cluster_role(name: str, rules: Sequence[dict]) -> dict:
    obj = k8s.make("rbac.authorization.k8s.io/v1", "ClusterRole", name,
                   labels=std_labels(name))
    obj["rules"] = list(rules)
    return obj


def cluster_role_binding(name: str, role: str, sa: str, namespace: str) -> dict:
    obj = k8s.make("rbac.authorization.k8s.io/v1", "ClusterRoleBinding", name,
                   labels=std_labels(name))
    obj["roleRef"] = {"apiGroup": "rbac.authorization.k8s.io",
                      "kind": "ClusterRole", "name": role}
    obj["subjects"] = [{"kind": "ServiceAccount", "name": sa,
                        "namespace": namespace}]
    return obj


def role(name: str, namespace: str, rules: Sequence[dict]) -> dict:
    """Namespaced Role: write verbs a component needs in ONE namespace
    must not ride a ClusterRole (blast-radius minimization — the
    warm-pod pool's pod/ConfigMap writes are the motivating case)."""
    obj = k8s.make("rbac.authorization.k8s.io/v1", "Role", name, namespace,
                   labels=std_labels(name))
    obj["rules"] = list(rules)
    return obj


def role_binding(name: str, namespace: str, role_name: str,
                 sa: str, sa_namespace: str) -> dict:
    obj = k8s.make("rbac.authorization.k8s.io/v1", "RoleBinding", name,
                   namespace, labels=std_labels(name))
    obj["roleRef"] = {"apiGroup": "rbac.authorization.k8s.io",
                      "kind": "Role", "name": role_name}
    obj["subjects"] = [{"kind": "ServiceAccount", "name": sa,
                        "namespace": sa_namespace}]
    return obj


def config_map(name: str, namespace: str, data: dict) -> dict:
    obj = k8s.make("v1", "ConfigMap", name, namespace, labels=std_labels(name))
    obj["data"] = {k: str(v) for k, v in data.items()}
    return obj


def crd(plural: str, kind: str, group: str, versions: Sequence[str],
        scope: str = "Namespaced",
        schema: Optional[dict] = None) -> dict:
    obj = k8s.make("apiextensions.k8s.io/v1", "CustomResourceDefinition",
                   f"{plural}.{group}")
    obj["spec"] = {
        "group": group,
        "names": {"kind": kind, "plural": plural,
                  "singular": kind.lower(), "listKind": f"{kind}List"},
        "scope": scope,
        "versions": [
            {"name": v, "served": True, "storage": i == 0,
             **({"schema": {"openAPIV3Schema": schema}} if schema else {})}
            for i, v in enumerate(versions)
        ],
    }
    return obj


def virtual_service(name: str, namespace: str, prefix: str, svc: str,
                    port: int, gateway: str = "kubeflow-gateway") -> dict:
    """Istio route — the idiom most reference packages emit
    (e.g. tf-job-operator.libsonnet:401-446)."""
    obj = k8s.make("networking.istio.io/v1alpha3", "VirtualService", name,
                   namespace, labels=std_labels(name))
    obj["spec"] = {
        "hosts": ["*"],
        "gateways": [gateway],
        "http": [{
            "match": [{"uri": {"prefix": prefix}}],
            "rewrite": {"uri": "/"},
            "route": [{"destination": {
                "host": f"{svc}.{namespace}.svc.cluster.local",
                "port": {"number": port}}}],
        }],
    }
    return obj
