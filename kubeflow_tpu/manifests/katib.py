"""Katib package: HP search (vizier core, suggestions, studyjob controller).

Reference: kubeflow/katib (vizier.libsonnet:4-20 core+mysql+REST+UI,
suggestion.libsonnet:50-66 per-algorithm services,
studyjobcontroller.libsonnet:131-147,294-323,368-408).
"""

from __future__ import annotations

from ..api import k8s
from ..api.trainingjob import KF_API_VERSION_V1ALPHA1, TPU_API_VERSION
from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"

SUGGESTION_ALGORITHMS = ("random", "grid", "hyperband", "bayesianoptimization")


@register("katib", "Hyperparameter search: StudyJob CRD, vizier core, "
                   "suggestion services (kubeflow/katib parity)")
def katib(namespace: str = "kubeflow",
          algorithms: str = ",".join(SUGGESTION_ALGORITHMS)) -> list[dict]:
    out: list[dict] = []
    study_crd = H.crd("studyjobs", "StudyJob", "kubeflow.org", ["v1alpha1"],
                      schema={
                          "type": "object",
                          "properties": {"spec": {
                              "type": "object",
                              "properties": {
                                  "studyName": {"type": "string"},
                                  "owner": {"type": "string"},
                                  "optimizationtype": {
                                      "type": "string",
                                      "enum": ["maximize", "minimize"]},
                                  "objectivevaluename": {"type": "string"},
                                  "suggestionSpec": {"type": "object"},
                                  "parameterconfigs": {"type": "array"},
                                  "workerSpec": {"type": "object"},
                                  "metricsnames": {"type": "array"},
                              }}}})
    out.append(study_crd)

    # Experiment CRD: the native search object (api/experiment.py) —
    # StudyJobs survive only as a compat shape converted into Experiments
    # by katib/studyjob.py
    exp_crd = H.crd("experiments", "Experiment", "kubeflow.org",
                    ["v1alpha1"],
                    schema={
                        "type": "object",
                        "properties": {"spec": {
                            "type": "object",
                            "properties": {
                                "objective": {
                                    "type": "object",
                                    "properties": {
                                        "type": {"type": "string",
                                                 "enum": ["maximize",
                                                          "minimize"]},
                                        "metric": {"type": "string"},
                                        "goal": {"type": "number"},
                                    }},
                                "algorithm": {"type": "object"},
                                "parameters": {"type": "array"},
                                "maxTrials": {"type": "integer"},
                                "parallelism": {"type": "integer"},
                                "maxFailedTrials": {"type": "integer"},
                                "earlyStopping": {"type": "object"},
                                "pbt": {"type": "object"},
                                "trialTemplate": {"type": "object"},
                                "injectParameters": {"type": "boolean"},
                            }}}})
    out.append(exp_crd)

    # vizier core + db (vizier.libsonnet:4-20)
    db = H.deployment("vizier-db", namespace, f"{IMG}/mysql:{VERSION}",
                      port=3306, env={"MYSQL_ROOT_PASSWORD": "vizier",
                                      "MYSQL_DATABASE": "vizier"})
    db_svc = H.service("vizier-db", namespace, 3306)
    core = H.deployment("vizier-core", namespace,
                        f"{IMG}/vizier-core:{VERSION}", port=6789,
                        env={"DB_ADDRESS": f"vizier-db.{namespace}:3306"})
    core_svc = H.service("vizier-core", namespace, 6789)
    ui = H.deployment("katib-ui", namespace, f"{IMG}/katib-ui:{VERSION}",
                      port=80)
    ui_svc = H.service("katib-ui", namespace, 80)
    ui_vs = H.virtual_service("katib-ui", namespace, "/katib/", "katib-ui", 80)
    out += [db, db_svc, core, core_svc, ui, ui_svc, ui_vs]

    # per-algorithm suggestion services (suggestion.libsonnet:50-66)
    for algo in algorithms.split(","):
        algo = algo.strip()
        if not algo:
            continue
        name = f"vizier-suggestion-{algo}"
        out.append(H.deployment(name, namespace,
                                f"{IMG}/suggestion-{algo}:{VERSION}",
                                port=6789))
        out.append(H.service(name, namespace, 6789))

    # studyjob controller (studyjobcontroller.libsonnet:294-323)
    sa = H.service_account("studyjob-controller", namespace)
    role = H.cluster_role("studyjob-controller", [
        {"apiGroups": ["kubeflow.org", "tpu.kubeflow.org"],
         "resources": ["studyjobs", "experiments", "tfjobs", "pytorchjobs",
                       "tpujobs", "mpijobs"], "verbs": ["*"]},
        {"apiGroups": ["batch"], "resources": ["jobs", "cronjobs"],
         "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["pods", "pods/log", "configmaps"],
         "verbs": ["*"]},
    ])
    binding = H.cluster_role_binding("studyjob-controller",
                                     "studyjob-controller",
                                     "studyjob-controller", namespace)
    ctrl = H.deployment("studyjob-controller", namespace,
                        f"{IMG}/studyjob-controller:{VERSION}",
                        service_account="studyjob-controller")
    # per-trial metrics collector template (studyjobcontroller.libsonnet:131-147)
    mc_template = H.config_map("metrics-collector-template", namespace, {
        "template": "builtin:metrics-collector-cronjob",
        "schedule": "*/1 * * * *",
    })
    out += [sa, role, binding, ctrl, mc_template]
    return out


@register("tpu-experiment-example", "Example Experiment: grid search over "
                                    "the ResNet-50 TPUJob's learning rate "
                                    "with median early stopping (the native "
                                    "search object reconciled by "
                                    "controllers/experiment.py)")
def tpu_experiment_example(namespace: str = "kubeflow",
                           name: str = "experiment-example",
                           max_trials: int = 8,
                           parallelism: int = 4) -> list[dict]:
    """Canonical Experiment example: grid over learning rate with median
    early stopping. The reconciler injects KFTPU_RUNTIME_SCHEDULE=1 into
    every trial so lr-variant trials share one compiled executable
    (compile-shape fingerprint split, runtime/recipe.py)."""
    exp = k8s.make(KF_API_VERSION_V1ALPHA1, "Experiment", name, namespace)
    exp["spec"] = {
        "objective": {"type": "maximize", "metric": "accuracy"},
        "algorithm": {"name": "grid", "settings": {"DefaultGrid": 8}},
        "parameters": [
            {"name": "--learning-rate", "type": "double",
             "min": 0.01, "max": 0.3},
        ],
        "maxTrials": max_trials,
        "parallelism": parallelism,
        "maxFailedTrials": 2,
        "earlyStopping": {"policy": "median", "minTrials": 3,
                          "startWindow": 2},
        "trialTemplate": {
            "apiVersion": TPU_API_VERSION, "kind": "TPUJob",
            "metadata": {"name": "$(trialName)", "namespace": namespace},
            "spec": {
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [{
                        "name": "worker",
                        "image": f"{IMG}/worker:{VERSION}",
                        "command": [
                            "python", "-m",
                            "kubeflow_tpu.runtime.worker",
                            "--workload", "resnet50",
                            "--steps", "200"],
                    }]}},
                }},
                "runPolicy": {"backoffLimit": 1},
                "sharding": {"data": -1},
                "checkpointDir": "/checkpoints/$(experimentName)/"
                                 "$(trialName)",
            },
        },
    }
    return [exp]
