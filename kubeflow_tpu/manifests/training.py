"""Training packages: the TPU job operator + legacy-kind CRDs + examples.

Reference packages: kubeflow/tf-training (tf-job-operator.libsonnet),
kubeflow/pytorch-job, kubeflow/mpi-job, kubeflow/examples/prototypes.
"""

from __future__ import annotations

from ..api import k8s
from ..obs.trace import SPAN_MAX_BYTES_ENV
from ..api.trainingjob import (KF_API_VERSION_V1BETA2,
                               TPU_API_VERSION)
from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
# per-image pin the auto-update bot retags independently (image_update.py)
WORKER_VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"

# Replica-count validation mirrored from the reference CRD schemas
# (tf-job-operator.libsonnet:14-46: Chief max 1, deliberately no pod-template
# validation per k8s#54579).
_REPLICA_SCHEMA = {
    "type": "object",
    "properties": {
        "replicas": {"type": "integer", "minimum": 1},
        "tpuTopology": {"type": "string", "pattern": r"^v\d+[a-z]*-\d+$"},
        "numSlices": {"type": "integer", "minimum": 1},
    },
}


def _job_schema(specs_key: str, max_one: list[str]) -> dict:
    props = {specs_key: {
        "type": "object",
        "properties": {
            t: ({**_REPLICA_SCHEMA,
                 "properties": {**_REPLICA_SCHEMA["properties"],
                                "replicas": {"type": "integer", "minimum": 1,
                                             "maximum": 1}}}
                if t in max_one else _REPLICA_SCHEMA)
            for t in ("TPU", "Chief", "Master", "Worker", "PS", "Launcher",
                      "Evaluator", "Coordinator", "Scheduler", "Server",
                      "Pserver", "Trainer")
        },
    },
        # step-engine knobs the operator renders into worker env
        # (TrainStepBuilder operator_knob fields; tests/test_lint.py
        # enforces this schema names every one)
        "weightUpdate": {"type": "string",
                         "enum": ["replicated", "sharded"]},
        # input-pipeline knobs (api/trainingjob.py InputSpec → the
        # KFTPU_INPUT_WORKERS / KFTPU_DEVICE_PREFETCH worker env;
        # tests/test_lint.py enforces the same full-path rule)
        "input": {"type": "object", "properties": {
            "workers": {"type": "integer", "minimum": 0},
            "devicePrefetch": {"type": "integer", "minimum": 0},
        }},
        # gang-scheduling knobs (api/trainingjob.py SchedulingPolicy →
        # the slice scheduler's queue/priority/preemptible; a job
        # carrying this block waits in Queued until the scheduler binds
        # it — tests/test_lint.py enforces the same full-path rule).
        # minChips/maxChips make the gang ELASTIC: the scheduler may
        # resize its binding inside the envelope at checkpoint
        # boundaries (shrink-to-survive/-admit, grow-to-fill, defrag —
        # docs/operations.md "Elastic resizing")
        "schedulingPolicy": {"type": "object", "properties": {
            "queue": {"type": "string"},
            "priority": {"type": "integer"},
            "preemptible": {"type": "boolean"},
            "minChips": {"type": "integer", "minimum": 1},
            "maxChips": {"type": "integer", "minimum": 1},
        }},
        # observability knobs (api/trainingjob.py ObsSpec → the worker's
        # KFTPU_SPAN_PATH span sink and KFTPU_OBS_METRICS_PORT /metrics
        # port; tests/test_lint.py enforces the same full-path rule)
        "observability": {"type": "object", "properties": {
            "spanPath": {"type": "string"},
            "metricsPort": {"type": "integer", "minimum": 0,
                            "maximum": 65535},
        }},
        # warm-start knobs (api/trainingjob.py WarmStartSpec → KFTPU_AOT
        # / KFTPU_AOT_DIR: the AOT serialized-executable rung above the
        # persistent compile cache — runtime/aot.py; tests/test_lint.py
        # enforces the same full-path rule)
        "warmStart": {"type": "object", "properties": {
            "aot": {"type": "boolean"},
            "aotDir": {"type": "string"},
        }},
        # multi-slice execution knobs (api/trainingjob.py MultisliceSpec
        # → KFTPU_MULTISLICE_PIPELINE / KFTPU_MULTISLICE_MICROBATCHES:
        # the MPMD pipeline-over-DCN path, one program per slice with
        # explicit activation transfers — parallel/multislice.py;
        # tests/test_lint.py enforces the same full-path rule)
        "multislice": {"type": "object", "properties": {
            "pipeline": {"type": "boolean"},
            "microbatches": {"type": "integer", "minimum": 1},
        }},
        # kernel-tier knobs (api/trainingjob.py KernelSpec →
        # KFTPU_KERNEL_ATTENTION / KFTPU_KERNEL_OPTIMIZER /
        # KFTPU_KERNEL_SERVING: flash attention, the fused-Adam Pallas
        # update, int8 quantized serving — every set knob is baked into
        # the recipe fingerprint + AOT step key; tests/test_lint.py
        # enforces the same full-path rule)
        "kernels": {"type": "object", "properties": {
            "attention": {"type": "string",
                          "enum": ["einsum", "flash", "ring"]},
            "optimizer": {"type": "string",
                          "enum": ["stock", "fused_adam"]},
            "serving": {"type": "string",
                        "enum": ["stock", "int8"]},
        }},
        # numeric-integrity sentinel knobs (api/trainingjob.py
        # IntegritySpec → KFTPU_INTEGRITY / _SPIKE_Z / _WINDOW /
        # _CHECK_EVERY: in-step NaN/Inf + loss-spike detection with LKG
        # rollback — runtime/sentinel.py; deliberately EXCLUDED from the
        # recipe fingerprint; tests/test_lint.py enforces the same
        # full-path rule)
        "integrity": {"type": "object", "properties": {
            "enabled": {"type": "boolean"},
            "spikeZ": {"type": "number", "exclusiveMinimum": 0},
            "windowSteps": {"type": "integer", "minimum": 2},
            "checkEverySteps": {"type": "integer", "minimum": 1},
        }},
        # persistent XLA compile cache dir override (defaults to the
        # namespace's shared cache when the operator carries
        # KFTPU_SHARED_CACHE_ROOT, else <checkpointDir>/.jax-compile-cache)
        "compileCacheDir": {"type": "string"},
    }
    return {"type": "object",
            "properties": {"spec": {"type": "object", "properties": props}}}


def _operator_deployment(namespace: str, gang_scheduling: bool,
                         shared_cache_root: str = "",
                         span_max_bytes: int = 0,
                         replicas: int = 2,
                         leader_elect: bool = True) -> list[dict]:
    from ..cluster.lease import OPERATOR_LEASE
    sa = H.service_account("tpu-job-operator", namespace)
    role = H.cluster_role("tpu-job-operator", [
        {"apiGroups": ["tpu.kubeflow.org", "kubeflow.org"],
         "resources": ["*"], "verbs": ["*"]},
        {"apiGroups": [""],
         "resources": ["pods", "services", "events", "configmaps"],
         "verbs": ["*"]},
        # node-health evidence: the operator folds failure events into
        # the kubeflow.org/health node annotation (scheduler/health.py)
        {"apiGroups": [""], "resources": ["nodes"],
         "verbs": ["get", "list", "watch", "patch"]},
        # gang-scheduling RBAC, the kube-batch podgroups rule analog
        # (tf-job-operator.libsonnet:298-307)
        *([{"apiGroups": ["scheduling.kubeflow.org"],
            "resources": ["podgroups"], "verbs": ["*"]}]
          if gang_scheduling else []),
    ])
    binding = H.cluster_role_binding("tpu-job-operator", "tpu-job-operator",
                                     "tpu-job-operator", namespace)
    from .observability import METRICS_PORT, scrape_annotations
    args = ["--controller=trainingjobs",
            f"--metrics-port={METRICS_PORT}"]
    if gang_scheduling:
        args.append("--enable-gang-scheduling")
    extra: list[dict] = []
    if leader_elect:
        # HA replica set: every replica watches, exactly one (the lease
        # holder) writes — controllers/__main__.py gates on the lease
        # named here (cluster/lease.py; identity = the pod name)
        args += ["--leader-elect", f"--lease-name={OPERATOR_LEASE}",
                 f"--lease-namespace={namespace}"]
        # lease RBAC is NAMESPACED (the lease lives beside the
        # deployment), like the scheduler's warm-pool role
        extra = [
            H.role("tpu-job-operator-leases", namespace, [
                {"apiGroups": ["coordination.k8s.io"],
                 "resources": ["leases"],
                 "verbs": ["get", "list", "watch", "create", "update"]},
            ]),
            H.role_binding("tpu-job-operator-leases", namespace,
                           "tpu-job-operator-leases",
                           "tpu-job-operator", namespace),
        ]
    dep = H.deployment("tpu-job-operator", namespace,
                       f"{IMG}/tpu-job-operator:{VERSION}", args=args,
                       service_account="tpu-job-operator", port=8443,
                       replicas=replicas if leader_elect else 1,
                       pod_annotations=scrape_annotations(METRICS_PORT),
                       # shared compile-cache service: with the root set
                       # the operator points every gang of a namespace
                       # at <root>/<namespace> on the tpu-compile-cache
                       # volume (runtime/compile_cache.py); the span
                       # rotation cap bounds the shared JSONL sink on
                       # long-lived deployments (obs/trace.py — the
                       # operator forwards it into every worker)
                       env=({**({"KFTPU_SHARED_CACHE_ROOT":
                                 shared_cache_root}
                                if shared_cache_root else {}),
                             **({SPAN_MAX_BYTES_ENV:
                                 str(int(span_max_bytes))}
                                if span_max_bytes else {})} or None))
    cm = H.config_map("tpu-job-operator-config", namespace, {
        "gang-scheduling": str(gang_scheduling).lower(),
        "coordinator-port": "8476",
    })
    return [sa, role, binding, *extra, cm, dep]


@register("tpu-job-operator", "TPUJob CRD + the gang-scheduling operator")
def tpu_job_operator(namespace: str = "kubeflow",
                     gang_scheduling: bool = True,
                     shared_cache_root: str = "",
                     span_max_bytes: int = 0,
                     replicas: int = 2,
                     leader_elect: bool = True) -> list[dict]:
    """``shared_cache_root`` (e.g. ``/mnt/kftpu-cache``) turns on the
    cluster-shared compile-cache service: the operator renders
    KFTPU_COMPILE_CACHE_DIR=<root>/<namespace> into every gang (one
    cache per namespace on the tpu-compile-cache volume — deploy that
    component alongside) instead of the per-job checkpoint-volume
    default (docs/operations.md "Warm starts and the compile cache").
    ``span_max_bytes`` caps the trace-span JSONL sink: at the cap the
    active file rotates to ``.1`` (one prior generation) so long-lived
    deployments never grow the sink unbounded; the operator forwards
    the cap into every worker (docs/operations.md "Goodput
    accounting").
    ``replicas``/``leader_elect`` are the control-plane HA knobs
    (docs/operations.md "Control-plane HA"): with leader election on
    (the default) the operator runs ``replicas`` pods behind a
    coordination.k8s.io Lease — every replica watches, only the lease
    holder writes, and a crashed leader fails over within one lease
    duration. ``leader_elect=False`` drops back to a single replica
    (two un-elected replicas would double-drive every gang)."""
    job_crd = H.crd("tpujobs", "TPUJob", "tpu.kubeflow.org", ["v1alpha1"],
                    schema=_job_schema("replicaSpecs", ["Coordinator"]))
    return [job_crd, *_operator_deployment(namespace, gang_scheduling,
                                           shared_cache_root,
                                           span_max_bytes,
                                           replicas=replicas,
                                           leader_elect=leader_elect)]


@register("tpu-compile-cache", "Cluster-shared XLA compile-cache volume: "
                               "one persistent cache per namespace, "
                               "mounted by every gang (warm starts)")
def tpu_compile_cache(namespace: str = "kubeflow",
                      size: str = "50Gi",
                      storage_class: str = "") -> list[dict]:
    """The volume behind the shared compile-cache service
    (runtime/compile_cache.py): a ReadWriteMany claim the operator's
    shared_cache_root points into. Workers mount it via their pod
    template; the operator only renders the env — a gang whose template
    lacks the mount degrades to its checkpoint-volume cache."""
    pvc = k8s.make("v1", "PersistentVolumeClaim", "tpu-compile-cache",
                   namespace)
    pvc["spec"] = {
        "accessModes": ["ReadWriteMany"],
        "resources": {"requests": {"storage": size}},
        **({"storageClassName": storage_class} if storage_class else {}),
    }
    return [pvc]


@register("tf-job-operator", "TFJob CRD served by the TPU operator "
                             "(kubeflow/tf-training parity)")
def tf_job_operator(namespace: str = "kubeflow") -> list[dict]:
    return [H.crd("tfjobs", "TFJob", "kubeflow.org", ["v1beta2", "v1beta1"],
                  schema=_job_schema("tfReplicaSpecs", ["Chief", "Master"]))]


@register("pytorch-operator", "PyTorchJob CRD served by the TPU operator "
                              "(kubeflow/pytorch-job parity)")
def pytorch_operator(namespace: str = "kubeflow") -> list[dict]:
    return [H.crd("pytorchjobs", "PyTorchJob", "kubeflow.org", ["v1beta2"],
                  schema=_job_schema("pytorchReplicaSpecs", ["Master"]))]


@register("mpi-operator", "MPIJob CRD (oneOf{tpuTopology,replicas}) served "
                          "by the TPU operator (kubeflow/mpi-job parity)")
def mpi_operator(namespace: str = "kubeflow") -> list[dict]:
    # The oneOf resource-quantity-first API (mpi-operator.libsonnet:27-77)
    schema = {
        "type": "object",
        "properties": {"spec": {
            "type": "object",
            "oneOf": [
                {"required": ["tpuTopology"]},
                {"required": ["replicas"]},
                {"required": ["replicaSpecs"]},
            ],
        }},
    }
    return [H.crd("mpijobs", "MPIJob", "kubeflow.org", ["v1alpha1"],
                  schema=schema)]


@register("chainer-operator", "ChainerJob CRD (ChainerMN over the MPI "
                              "hostlist contract) served by the TPU operator "
                              "(kubeflow/chainer-job parity)")
def chainer_operator(namespace: str = "kubeflow") -> list[dict]:
    return [H.crd("chainerjobs", "ChainerJob", "kubeflow.org", ["v1alpha1"],
                  schema=_job_schema("chainerReplicaSpecs", ["Master"]))]


@register("mxnet-operator", "MXJob CRD (DMLC scheduler/server/worker env) "
                            "served by the TPU operator "
                            "(kubeflow/mxnet-job parity)")
def mxnet_operator(namespace: str = "kubeflow") -> list[dict]:
    return [H.crd("mxjobs", "MXJob", "kubeflow.org", ["v1alpha1"],
                  schema=_job_schema("mxReplicaSpecs", ["Scheduler"]))]


@register("paddle-operator", "PaddleJob CRD (PADDLE_* pserver/trainer env) "
                             "served by the TPU operator "
                             "(kubeflow/paddle-job parity)")
def paddle_operator(namespace: str = "kubeflow") -> list[dict]:
    return [H.crd("paddlejobs", "PaddleJob", "kubeflow.org", ["v1alpha1"],
                  schema=_job_schema("paddleReplicaSpecs", []))]


@register("tpu-scheduler", "Gang-scheduling queue: the quota-aware slice "
                           "scheduler binding TPUJobs to ICI sub-slices "
                           "(the kube-batch/Volcano slot of the reference)")
def tpu_scheduler(namespace: str = "kubeflow",
                  backfill: bool = True,
                  preemption: bool = True,
                  queues: dict | None = None,
                  health: dict | None = None,
                  elastic: bool = True,
                  grow: bool = True,
                  defrag: bool = True,
                  grow_cooldown_seconds: float = 300.0,
                  warm_pods: int = 0,
                  replicas: int = 2,
                  leader_elect: bool = True) -> list[dict]:
    """``queues`` is the SchedulerConfig wire shape
    (scheduler/queue.py), e.g. ``{"research": {"quotaChips":
    {"team-a": 32, "*": 64}}}`` — per-queue, per-namespace bound-chip
    quotas ("*" is the default for unlisted namespaces). ``health`` is
    the node-health policy block (scheduler/health.py HealthConfig wire
    shape): ``{"enabled": true, "halfLifeSeconds": 600,
    "quarantineThreshold": 3, "releaseThreshold": 1,
    "quarantineSeconds": 900}`` — omitted keys keep the defaults;
    ``{"enabled": false}`` turns the whole quarantine feedback loop
    off (docs/operations.md "Node health and quarantine").
    ``elastic``/``grow``/``defrag``/``grow_cooldown_seconds`` are the
    elastic-resizing policy switches (scheduler/queue.py
    SchedulerConfig; docs/operations.md "Elastic resizing"): the
    master resize switch, grow-to-fill, defrag migration, and the
    per-gang hysteresis between grows/migrations. ``warm_pods`` sizes
    the warm-pod pool (scheduler/warmpool.py): the scheduler keeps up
    to N pre-initialized pods on idle hosts and binds prefer adopting
    them — rebinds/resizes start warm (docs/operations.md "Warm starts
    and the compile cache"). ``replicas``/``leader_elect``: the
    control-plane HA knobs — see tpu_job_operator; the scheduler's
    replicas elect through the tpu-scheduler Lease (cluster/lease.py,
    docs/operations.md "Control-plane HA")."""
    import json

    from ..cluster.lease import SCHEDULER_LEASE
    from ..scheduler.health import HealthConfig
    sa = H.service_account("tpu-scheduler", namespace)
    role = H.cluster_role("tpu-scheduler", [
        {"apiGroups": ["tpu.kubeflow.org"],
         "resources": ["tpujobs"], "verbs": ["get", "list", "watch",
                                             "patch", "update"]},
        {"apiGroups": [""],
         "resources": ["pods", "configmaps"],
         "verbs": ["get", "list", "watch"]},
        # nodes are read AND written: the health pass patches the
        # quarantine / health-score annotations (scheduler/health.py)
        {"apiGroups": [""], "resources": ["nodes"],
         "verbs": ["get", "list", "watch", "patch"]},
    ])
    binding = H.cluster_role_binding("tpu-scheduler", "tpu-scheduler",
                                     "tpu-scheduler", namespace)
    # warm-pod pool writes are NAMESPACED: the pool's pods and the
    # tpu-warm-pool slots ConfigMap live only in the scheduler's own
    # namespace (scheduler/warmpool.py WARM_POOL_NAMESPACE), so the
    # create/delete/patch verbs ride a Role there instead of widening
    # the cluster-wide read grant above
    warm_role = H.role("tpu-scheduler-warm-pool", namespace, [
        {"apiGroups": [""], "resources": ["pods", "configmaps"],
         "verbs": ["create", "delete", "patch"]},
    ])
    warm_binding = H.role_binding("tpu-scheduler-warm-pool", namespace,
                                  "tpu-scheduler-warm-pool",
                                  "tpu-scheduler", namespace)
    cm = H.config_map("tpu-scheduler-config", namespace, {
        "config.json": json.dumps({
            "backfill": backfill, "preemption": preemption,
            "elastic": elastic, "grow": grow, "defrag": defrag,
            "growCooldownSeconds": grow_cooldown_seconds,
            "warmPods": warm_pods,
            "queues": queues or {},
            # render the FULL health block (defaults made explicit) so
            # the deployed knobs are discoverable with kubectl, and
            # round-trip through HealthConfig so a typo'd key fails at
            # render time, not silently at scheduler parse time
            "health": HealthConfig.from_dict(health).to_dict()},
            indent=1),
    })
    from .observability import METRICS_PORT, scrape_annotations
    args = ["--controllers=scheduler",
            f"--metrics-port={METRICS_PORT}"]
    extra: list[dict] = []
    if leader_elect:
        # HA: N replicas, one lease holder writes (cluster/lease.py;
        # controllers/__main__.py --leader-elect gates every hosted
        # controller on the lease named here)
        args += ["--leader-elect", f"--lease-name={SCHEDULER_LEASE}",
                 f"--lease-namespace={namespace}"]
        extra = [
            H.role("tpu-scheduler-leases", namespace, [
                {"apiGroups": ["coordination.k8s.io"],
                 "resources": ["leases"],
                 "verbs": ["get", "list", "watch", "create", "update"]},
            ]),
            H.role_binding("tpu-scheduler-leases", namespace,
                           "tpu-scheduler-leases",
                           "tpu-scheduler", namespace),
        ]
    dep = H.deployment("tpu-scheduler", namespace,
                       f"{IMG}/tpu-job-operator:{VERSION}",
                       args=args,
                       service_account="tpu-scheduler", port=8443,
                       replicas=replicas if leader_elect else 1,
                       pod_annotations=scrape_annotations(METRICS_PORT))
    return [sa, role, binding, warm_role, warm_binding, *extra, cm, dep]


@register("openmpi-controller", "Slice-sidecar config: lifecycle hooks for "
                                "gang workers (components/openmpi-controller analog)")
def openmpi_controller(namespace: str = "kubeflow") -> list[dict]:
    # The reference's sidecar sequenced MPI workers via SIGCONT files and
    # master-phase polling (controller.py:17-23). The TPU analog is the
    # jax.distributed barrier; this ships the sidecar config used for
    # non-JAX payloads needing start sequencing.
    return [H.config_map("slice-sidecar-config", namespace, {
        "wait-mode": "coordinator-barrier",
        "poll-interval-s": "10",
    })]


@register("tpu-job-simple", "Example TPUJob: ResNet-50 synthetic benchmark "
                            "(examples/prototypes/tf-job-simple-v1.jsonnet analog)")
def tpu_job_simple(namespace: str = "kubeflow", name: str = "tpu-job-simple",
                   topology: str = "v5e-8", steps: int = 100,
                   global_batch: int = 1024,
                   fused_blocks: bool = False,
                   fused_routing: dict | None = None,
                   weight_update: str = "",
                   input_workers: int | None = None,
                   device_prefetch: int | None = None,
                   backoff_limit: int = 3,
                   clean_pod_policy: str = "Running",
                   gang_scheduling: bool = True,
                   active_deadline_seconds: int | None = None,
                   ttl_seconds_after_finished: int | None = None,
                   restart_backoff_seconds: float = 0.0,
                   restart_backoff_max_seconds: float = 300.0,
                   stall_timeout_seconds: int | None = None,
                   max_anomaly_rollbacks: int = 2,
                   integrity: bool | None = None,
                   integrity_spike_z: float | None = None,
                   integrity_window_steps: int | None = None,
                   integrity_check_every_steps: int | None = None,
                   queue: str | None = None,
                   priority: int | None = None,
                   preemptible: bool | None = None,
                   min_chips: int | None = None,
                   max_chips: int | None = None,
                   span_path: str | None = None,
                   obs_metrics_port: int | None = None,
                   aot: bool | None = None,
                   aot_dir: str | None = None,
                   num_slices: int = 1,
                   multislice_pipeline: bool | None = None,
                   multislice_microbatches: int | None = None
                   ) -> list[dict]:
    """fused_blocks opts into the ghost-BN fused bottleneck kernels
    (docs/training.md --fused-blocks; per-block batch/spatial routing).
    ``fused_routing`` pins the per-geometry kernel routing to a
    chip-measured table (the ``bench.py --mode fused-blocks`` output's
    ``routes`` dict): it renders as a ConfigMap mounted into the worker
    with KFTPU_FUSED_ROUTING_TABLE pointing at it — measured beats
    modeled (PERF.md round 5). ``weight_update="sharded"`` opts the gang
    into the ZeRO-2 cross-replica sharded weight update (spec.weightUpdate
    → KFTPU_WEIGHT_UPDATE; PERF.md "Weight-update sharding").
    ``input_workers``/``device_prefetch`` render the overlapped input
    pipeline's spec.input knobs (→ KFTPU_INPUT_WORKERS /
    KFTPU_DEVICE_PREFETCH; docs/training.md "Input pipeline") — set
    input_workers when the job reads record shards (spec.dataDir).

    The run-policy knobs mirror RunPolicy (api/trainingjob.py) one-to-one
    and render through it, so the example manifest can express the FULL
    failure-handling surface (docs/operations.md "Failure handling"):
    ``backoff_limit``/``clean_pod_policy``/``gang_scheduling``/
    ``active_deadline_seconds``/``ttl_seconds_after_finished`` (the
    classic tf-operator policy), ``restart_backoff_seconds`` +
    ``restart_backoff_max_seconds`` (exponential backoff with jitter
    between gang restarts — restart-storm protection; spec
    restartBackoffSeconds/restartBackoffMaxSeconds), and
    ``stall_timeout_seconds`` (the hung-chief stall watchdog; spec
    stallTimeoutSeconds), and ``max_anomaly_rollbacks`` (the numeric-
    integrity sentinel's LKG-rollback budget, separate from
    backoffLimit; spec maxAnomalyRollbacks — docs/operations.md
    "Numeric integrity").

    ``integrity`` + ``integrity_spike_z``/``integrity_window_steps``/
    ``integrity_check_every_steps`` render spec.integrity
    (api/trainingjob.py IntegritySpec → KFTPU_INTEGRITY / _SPIKE_Z /
    _WINDOW / _CHECK_EVERY): the in-step NaN/Inf + loss-spike sentinel
    with last-known-good rollback (docs/operations.md "Numeric
    integrity").

    ``queue``/``priority``/``preemptible`` render spec.schedulingPolicy
    (api/trainingjob.py SchedulingPolicy): set ANY of them — including
    explicitly to a default value like ``priority=0`` — and the job
    becomes scheduler-managed: it waits in ``Queued`` until the slice
    scheduler (kubeflow_tpu/scheduler/) binds its gang, and a
    ``preemptible`` gang may be reclaimed (checkpoint + requeue) for a
    higher-priority job (docs/operations.md "Scheduling, queues, and
    quotas"). ``min_chips``/``max_chips`` make the gang ELASTIC: the
    scheduler may resize its binding anywhere inside the envelope at
    checkpoint boundaries — shrink to survive a lost host or admit a
    blocked head, grow into idle chips, migrate to defragment
    (docs/operations.md "Elastic resizing"). Leave every scheduling
    knob unset (None) for the legacy immediate-create path.

    ``span_path``/``obs_metrics_port`` render spec.observability
    (api/trainingjob.py ObsSpec → KFTPU_SPAN_PATH /
    KFTPU_OBS_METRICS_PORT): the worker's trace-span JSONL sink and its
    own /metrics port (docs/operations.md "Observability").

    ``aot``/``aot_dir`` render spec.warmStart (api/trainingjob.py
    WarmStartSpec → KFTPU_AOT / KFTPU_AOT_DIR): the AOT serialized-
    executable warm start — rebinds/resizes load the keyed compiled
    step and skip XLA entirely (docs/operations.md "Warm starts and
    the compile cache").

    ``num_slices`` + ``multislice_pipeline``/``multislice_microbatches``
    render a multi-slice gang and spec.multislice (api/trainingjob.py
    MultisliceSpec → KFTPU_MULTISLICE_PIPELINE /
    KFTPU_MULTISLICE_MICROBATCHES): the MPMD pipeline-over-DCN path —
    one program per slice, explicit activation transfers, 1F1B
    microbatch schedule (docs/training.md "Multi-slice training")."""
    command = ["python", "-m", "kubeflow_tpu.runtime.worker",
               "--workload", "resnet50",
               "--steps", str(steps),
               "--global-batch", str(global_batch)]
    if fused_blocks:
        command.append("--fused-blocks")
    container: dict = {
        "name": "worker",
        "image": f"{IMG}/worker:{WORKER_VERSION}",
        "command": command,
    }
    pod_spec: dict = {"containers": [container]}
    out: list[dict] = []
    if fused_routing is not None:
        if not fused_blocks:
            # a mounted table the worker never reads is a silent no-op
            # the user would mistake for pinned routing
            raise ValueError("fused_routing requires fused_blocks=True "
                             "(only the fused path consults the table)")
        import json
        mount_dir = "/etc/kubeflow/fused-routing"
        cm = H.config_map(f"{name}-fused-routing", namespace, {
            "routing.json": json.dumps({"routes": fused_routing},
                                       indent=1)})
        out.append(cm)
        container["env"] = [{"name": "KFTPU_FUSED_ROUTING_TABLE",
                             "value": f"{mount_dir}/routing.json"}]
        container["volumeMounts"] = [{"name": "fused-routing",
                                      "mountPath": mount_dir,
                                      "readOnly": True}]
        pod_spec["volumes"] = [{"name": "fused-routing",
                                "configMap": {
                                    "name": cm["metadata"]["name"]}}]
    from ..api.trainingjob import RunPolicy
    run_policy = RunPolicy(
        clean_pod_policy=clean_pod_policy,
        backoff_limit=backoff_limit,
        active_deadline_seconds=active_deadline_seconds,
        gang_scheduling=gang_scheduling,
        ttl_seconds_after_finished=ttl_seconds_after_finished,
        restart_backoff_seconds=restart_backoff_seconds,
        restart_backoff_max_seconds=restart_backoff_max_seconds,
        stall_timeout_seconds=stall_timeout_seconds,
        max_anomaly_rollbacks=max_anomaly_rollbacks)
    job = k8s.make(TPU_API_VERSION, "TPUJob", name, namespace)
    tpu_spec: dict = {
        "tpuTopology": topology,
        "template": {"spec": pod_spec},
    }
    if num_slices != 1:
        tpu_spec["numSlices"] = num_slices
    job["spec"] = {
        "replicaSpecs": {"TPU": tpu_spec},
        "runPolicy": run_policy.to_dict(),
        "sharding": {"data": -1},
    }
    if weight_update:
        from ..api.trainingjob import validate_weight_update
        job["spec"]["weightUpdate"] = validate_weight_update(weight_update)
    if input_workers is not None or device_prefetch is not None:
        from ..api.trainingjob import InputSpec
        ispec = InputSpec(workers=input_workers,
                          device_prefetch=device_prefetch)
        ispec.validate()
        job["spec"]["input"] = ispec.to_dict()
    if integrity is not None or integrity_spike_z is not None or \
            integrity_window_steps is not None or \
            integrity_check_every_steps is not None:
        from ..api.trainingjob import IntegritySpec
        sspec = IntegritySpec(
            enabled=integrity, spike_z=integrity_spike_z,
            window_steps=integrity_window_steps,
            check_every_steps=integrity_check_every_steps)
        sspec.validate()
        job["spec"]["integrity"] = sspec.to_dict()
    if queue is not None or priority is not None or \
            preemptible is not None or min_chips is not None or \
            max_chips is not None:
        from ..api.trainingjob import SchedulingPolicy
        policy = SchedulingPolicy(queue=queue or "",
                                  priority=priority or 0,
                                  preemptible=bool(preemptible),
                                  min_chips=min_chips,
                                  max_chips=max_chips)
        policy.validate()
        job["spec"]["schedulingPolicy"] = policy.to_dict()
    if span_path is not None or obs_metrics_port is not None:
        from ..api.trainingjob import ObsSpec
        ospec = ObsSpec(span_path=span_path,
                        metrics_port=obs_metrics_port)
        ospec.validate()
        job["spec"]["observability"] = ospec.to_dict()
    if aot is not None or aot_dir is not None:
        from ..api.trainingjob import WarmStartSpec
        wspec = WarmStartSpec(aot=aot, aot_dir=aot_dir)
        wspec.validate()
        job["spec"]["warmStart"] = wspec.to_dict()
    if multislice_pipeline is not None or \
            multislice_microbatches is not None:
        from ..api.trainingjob import MultisliceSpec
        mspec = MultisliceSpec(pipeline=multislice_pipeline,
                               microbatches=multislice_microbatches)
        mspec.validate()
        job["spec"]["multislice"] = mspec.to_dict()
        if mspec.pipeline_enabled:
            if fused_blocks:
                # the wholesale command rewrite below would silently
                # drop --fused-blocks (and the MPMD path stages the
                # pipelined LM, not a resnet) — same rule as
                # fused_routing-without-fused_blocks above
                raise ValueError(
                    "fused_blocks and multislice_pipeline are mutually "
                    "exclusive (the MPMD path runs the pipelined LM, "
                    "not the fused-resnet workload)")
            # the MPMD path stages the pipelined LM, not the image
            # model; the CLI flag only rides along when the pipeline is
            # actually ON (pipeline=False blocks keep the default
            # command — the env render carries the knobs either way)
            container["command"] = [
                "python", "-m", "kubeflow_tpu.runtime.worker",
                "--workload", "transformer-pipelined",
                "--steps", str(steps),
                "--global-batch", str(global_batch),
                "--multislice-pipeline"]
    out.append(job)
    return out


@register("tf-job-simple", "Example TFJob: 1 chief + 1 worker CPU benchmark "
                           "(tf-job-simple-v1.jsonnet parity)")
def tf_job_simple(namespace: str = "kubeflow",
                  name: str = "tf-job-simple") -> list[dict]:
    tmpl = {"spec": {"containers": [{
        "name": "tensorflow", "image": f"{IMG}/tf-cnn-benchmark:{VERSION}",
        "args": ["--model=resnet50", "--device=cpu", "--batch_size=32",
                 "--data_name=synthetic"]}],
        "restartPolicy": "OnFailure"}}
    job = k8s.make(KF_API_VERSION_V1BETA2, "TFJob", name, namespace)
    job["spec"] = {"tfReplicaSpecs": {
        "Chief": {"replicas": 1, "template": tmpl},
        "Worker": {"replicas": 1, "template": tmpl},
    }}
    return [job]
