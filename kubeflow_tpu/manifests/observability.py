"""Observability packages: prometheus, metric-collector, TPU device plugin.

Reference: kubeflow/gcp/prototypes/prometheus.jsonnet, metric-collector
(kubeflow-readiness.py + metric-collector.jsonnet), and the GPU-driver
DaemonSet slot (kubeflow/gcp/gpu-driver.libsonnet — here the TPU device
plugin, SURVEY §2.6).
"""

from __future__ import annotations

from ..api import k8s
from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"

# the port the control-plane processes serve /metrics on (the
# controller-manager / scheduler --metrics-port default the deployments
# below render)
METRICS_PORT = 8080


def scrape_annotations(port: int, path: str = "/metrics") -> dict:
    """The annotation-based Prometheus discovery contract every scrape
    surface in the platform advertises (controller manager, scheduler,
    model server, probers, workers via spec.observability.metricsPort) —
    one helper so the keys cannot drift between components."""
    return {
        "prometheus.io/scrape": "true",
        "prometheus.io/port": str(port),
        "prometheus.io/path": path,
    }


@register("prometheus", "Prometheus deployment (gcp/prototypes/prometheus parity)")
def prometheus(namespace: str = "kubeflow-monitoring") -> list[dict]:
    ns = k8s.make("v1", "Namespace", namespace)
    cm = H.config_map("prometheus-config", namespace, {
        "prometheus.yml": (
            "global: {scrape_interval: 30s}\n"
            "scrape_configs:\n"
            "- job_name: kubeflow\n"
            "  kubernetes_sd_configs: [{role: pod}]\n"
        ),
    })
    sa = H.service_account("prometheus", namespace)
    role = H.cluster_role("prometheus", [
        {"apiGroups": [""], "resources": ["nodes", "services", "endpoints",
                                          "pods"],
         "verbs": ["get", "list", "watch"]},
    ])
    binding = H.cluster_role_binding("prometheus", "prometheus", "prometheus",
                                     namespace)
    dep = H.deployment("prometheus", namespace, f"{IMG}/prometheus:{VERSION}",
                       port=9090, service_account="prometheus")
    svc = H.service("prometheus", namespace, 9090)
    return [ns, cm, sa, role, binding, dep, svc]


@register("metric-collector", "Availability prober exporting "
                              "kubeflow_availability (metric-collector parity)")
def metric_collector(namespace: str = "kubeflow",
                     target_url: str = "http://centraldashboard.kubeflow") -> list[dict]:
    dep = H.deployment("metric-collector", namespace,
                       f"{IMG}/metric-collector:{VERSION}", port=8000,
                       env={"TARGET_URL": target_url,
                            "PROBE_INTERVAL_S": "30"},
                       pod_annotations=scrape_annotations(8000))
    svc = H.service("metric-collector", namespace, 8000)
    svc["metadata"].setdefault("annotations", {}).update(
        scrape_annotations(8000))
    return [dep, svc]


@register("deploy-prober", "End-to-end deploy drill prober "
                           "(click-to-deploy prober parity, "
                           "testing/test_deploy_app.py)")
def deploy_prober(namespace: str = "kubeflow",
                  bootstrap_url: str =
                  "http://kubeflow-bootstrapper.kubeflow-admin:8085",
                  interval_s: int = 600) -> list[dict]:
    dep = H.deployment("deploy-prober", namespace,
                       f"{IMG}/deploy-prober:{VERSION}", port=8000,
                       env={"BOOTSTRAP_URL": bootstrap_url,
                            "PROBE_INTERVAL_S": str(interval_s)},
                       pod_annotations=scrape_annotations(8000))
    svc = H.service("deploy-prober", namespace, 8000)
    svc["metadata"].setdefault("annotations", {}).update(
        scrape_annotations(8000))
    return [dep, svc]


@register("tpu-device-plugin", "TPU device-plugin DaemonSet (the GPU-driver "
                               "installer slot, gcp/gpu-driver.libsonnet)")
def tpu_device_plugin(namespace: str = "kube-system") -> list[dict]:
    ds = k8s.make("apps/v1", "DaemonSet", "tpu-device-plugin", namespace,
                  labels=H.std_labels("tpu-device-plugin"))
    ds["spec"] = {
        "selector": {"matchLabels": {H.APP_LABEL: "tpu-device-plugin"}},
        "template": {
            "metadata": {"labels": H.std_labels("tpu-device-plugin")},
            "spec": {
                "nodeSelector": {"cloud.google.com/gke-tpu-accelerator": ""},
                "tolerations": [{"operator": "Exists"}],
                "containers": [{
                    "name": "device-plugin",
                    "image": f"{IMG}/tpu-device-plugin:{VERSION}",
                    "volumeMounts": [{"name": "device-plugin",
                                      "mountPath": "/var/lib/kubelet/device-plugins"}],
                }],
                "volumes": [{"name": "device-plugin",
                             "hostPath": {
                                 "path": "/var/lib/kubelet/device-plugins"}}],
            },
        },
    }
    # match-all selector: GKE labels TPU nodes with non-empty accelerator
    # values; the empty selector value is patched per node pool at install
    return [ds]
