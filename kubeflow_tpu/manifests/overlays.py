"""Config flavors: the kustomize-v2 base+overlay merge analog.

The reference's next-gen package manager walks a config layout of one base
plus named overlays (bootstrap/config/{base,overlays/{basic_auth,gcp,...}})
and merges overlay kustomizations over the base with param substitution
(bootstrap/v2/pkg/kfapp/kustomize/kustomize.go:596-683 MergeKustomization).

Here a flavor is a typed overlay over the KfDef spec: components to add or
drop plus per-component param overrides, resolved at generate time so
`kfctl generate --flavor=iap` and `--flavor=basic_auth` render different
manifest sets from the same app. Explicit user componentParams always win
over flavor params (the kustomize behavior: the more specific layer wins).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True)
class Flavor:
    name: str
    description: str = ""
    components_add: tuple = ()
    components_remove: tuple = ()
    component_params: dict = field(default_factory=dict)


FLAVORS: dict[str, Flavor] = {}


def _register(flavor: Flavor) -> Flavor:
    FLAVORS[flavor.name] = flavor
    return flavor


# base = the KfDef component list untouched (bootstrap/config/base)
_register(Flavor(
    name="local",
    description="no cloud ingress; gatekeeper only "
                "(overlays/ksonnet local flavor)",
))

_register(Flavor(
    name="iap",
    description="GCP IAP-protected ingress "
                "(bootstrap/config/kfctl_iap.yaml overlay)",
    components_add=("iap-ingress", "cert-manager", "cloud-endpoints"),
    components_remove=("basic-auth-ingress",),
    component_params={
        "iap-ingress": {"upstream": "centraldashboard:80"},
    },
))

_register(Flavor(
    name="basic_auth",
    description="gatekeeper-backed auth ingress "
                "(bootstrap/config/overlays/basic_auth)",
    components_add=("basic-auth-ingress", "gatekeeper"),
    components_remove=("iap-ingress", "cert-manager", "cloud-endpoints"),
    component_params={
        "basic-auth-ingress": {"upstream": "centraldashboard:80"},
    },
))


def flavor_names() -> list[str]:
    return sorted(FLAVORS)


# -- on-disk config layouts (the kustomize-v2 repo walk) ---------------------

def walk_config_dir(root: str) -> tuple[Flavor, dict[str, Flavor]]:
    """Walk a config layout on disk (the reference's
    bootstrap/config/{base,overlays/*} shape; kustomize.go:524-560
    mapDirs walks the manifests repo for kustomization leaves the same
    way). Returns (base, overlays):

        <root>/base/config.yaml              components, componentParams
        <root>/overlays/<name>/config.yaml   componentsAdd/Remove,
                                             componentParams, description

    Overlay names may nest (overlays/gcp/iap → "gcp/iap"). A missing
    base directory is an error; an empty overlays tree is fine."""
    import os

    from ..utils import yamlio

    def read(path: str) -> dict:
        return yamlio.load_file(path) or {}

    base_path = os.path.join(root, "base", "config.yaml")
    if not os.path.exists(base_path):
        raise FileNotFoundError(
            f"config dir {root!r} has no base/config.yaml")
    raw = read(base_path)
    base = Flavor(name="", description=str(raw.get("description", "")),
                  components_add=tuple(raw.get("components") or ()),
                  component_params=dict(raw.get("componentParams") or {}))

    overlays: dict[str, Flavor] = {}
    overlays_root = os.path.join(root, "overlays")
    if os.path.isdir(overlays_root):
        for dirpath, _dirnames, filenames in os.walk(overlays_root):
            if "config.yaml" not in filenames:
                continue
            name = os.path.relpath(dirpath, overlays_root).replace(
                os.sep, "/")
            raw = read(os.path.join(dirpath, "config.yaml"))
            overlays[name] = Flavor(
                name=name,
                description=str(raw.get("description", "")),
                components_add=tuple(raw.get("componentsAdd") or ()),
                components_remove=tuple(raw.get("componentsRemove") or ()),
                component_params=dict(raw.get("componentParams") or {}))
    return base, overlays


def resolve_config_dir(root: str, components: list[str],
                       component_params: dict, flavor: str = ""
                       ) -> tuple[list[str], dict]:
    """Resolve (components, params) from an on-disk config layout: the
    base config supplies the component list, the named overlay merges
    over it (MergeKustomization), and the caller's spec components /
    params merge last (the more specific layer wins — user > overlay >
    base). Unknown overlay names fall back to the built-in FLAVORS."""
    base, overlays = walk_config_dir(root)
    out_components = list(base.components_add)
    out_params = {k: dict(v) for k, v in base.component_params.items()}

    if flavor:
        if flavor in overlays:
            f = overlays[flavor]
        elif flavor in FLAVORS:
            f = FLAVORS[flavor]
        else:
            known = sorted(set(overlays) | set(FLAVORS))
            raise KeyError(f"unknown flavor {flavor!r}; known: {known}")
        out_components = [c for c in out_components
                          if c not in f.components_remove]
        for c in f.components_add:
            if c not in out_components:
                out_components.append(c)
        for comp, params in f.component_params.items():
            out_params.setdefault(comp, {}).update(params)

    for c in components:
        if c not in out_components:
            out_components.append(c)
    for comp, params in component_params.items():
        out_params.setdefault(comp, {}).update(params)  # user params win
    return out_components, out_params


def resolve(components: list[str],
            component_params: dict[str, dict[str, Any]],
            flavor: str = "") -> tuple[list[str], dict[str, dict[str, Any]]]:
    """Merge a flavor over the base (components, params); returns the
    effective pair without mutating the inputs. Unknown flavor raises."""
    if not flavor or flavor == "local":
        if flavor and flavor not in FLAVORS:
            raise KeyError(
                f"unknown flavor {flavor!r}; known: {flavor_names()}")
        return list(components), {k: dict(v)
                                  for k, v in component_params.items()}
    if flavor not in FLAVORS:
        raise KeyError(f"unknown flavor {flavor!r}; known: {flavor_names()}")
    f = FLAVORS[flavor]
    out_components = [c for c in components if c not in f.components_remove]
    for c in f.components_add:
        if c not in out_components:
            out_components.append(c)
    out_params = {k: dict(v) for k, v in component_params.items()}
    for comp, params in f.component_params.items():
        merged = dict(params)
        merged.update(out_params.get(comp, {}))  # user params win
        out_params[comp] = merged
    return out_components, out_params
