"""The AWS package: ALB ingress, EFS/FSx CSI storage, istio ingress.

Reference: kubeflow/aws/prototypes/ (7 prototypes, ~2.3k LoC jsonnet) —
alb-ingress-controller, EFS/FSx CSI drivers + PVs, istio-ingress. On a TPU
build these matter for EKS-hosted control planes fronting cloud TPU slices
(the training data path stays on GCP, but the reference treats the AWS
catalog as first-class and so do we).
"""

from __future__ import annotations

from ..api import k8s
from . import helpers as H
from .registry import register

VERSION = "v0.1.0"


@register("alb-ingress-controller", "AWS ALB ingress controller "
                                    "(kubeflow/aws alb-ingress parity)")
def alb_ingress_controller(namespace: str = "kubeflow",
                           cluster_name: str = "kubeflow-tpu") -> list[dict]:
    sa = H.service_account("alb-ingress-controller", namespace)
    role = H.cluster_role("alb-ingress-controller", [
        {"apiGroups": ["", "extensions", "networking.k8s.io"],
         "resources": ["configmaps", "endpoints", "events", "ingresses",
                       "ingresses/status", "services", "nodes", "pods",
                       "secrets"],
         "verbs": ["create", "get", "list", "update", "watch", "patch"]},
    ])
    binding = H.cluster_role_binding("alb-ingress-controller",
                                     "alb-ingress-controller",
                                     "alb-ingress-controller", namespace)
    dep = H.deployment(
        "alb-ingress-controller", namespace,
        "docker.io/amazon/aws-alb-ingress-controller:v1.1.2",
        args=["--ingress-class=alb", f"--cluster-name={cluster_name}"],
        service_account="alb-ingress-controller", port=10254)
    return [sa, role, binding, dep]


def _csi_driver(name: str, image: str, namespace: str) -> list[dict]:
    sa = H.service_account(f"{name}-csi-controller", namespace)
    role = H.cluster_role(f"{name}-csi", [
        {"apiGroups": [""],
         "resources": ["persistentvolumes", "persistentvolumeclaims",
                       "nodes", "events"],
         "verbs": ["get", "list", "watch", "create", "delete", "update"]},
        {"apiGroups": ["storage.k8s.io"],
         "resources": ["storageclasses", "csinodes", "volumeattachments"],
         "verbs": ["get", "list", "watch", "update"]},
    ])
    binding = H.cluster_role_binding(f"{name}-csi", f"{name}-csi",
                                     f"{name}-csi-controller", namespace)
    # node plugin DaemonSet (the csi-driver deployment shape the reference
    # aws package installs)
    ds = {
        "apiVersion": "apps/v1", "kind": "DaemonSet",
        "metadata": {"name": f"{name}-csi-node", "namespace": namespace,
                     "labels": H.std_labels(f"{name}-csi-node")},
        "spec": {
            "selector": {"matchLabels": {"app": f"{name}-csi-node"}},
            "template": {
                "metadata": {"labels": {"app": f"{name}-csi-node"}},
                "spec": {
                    "serviceAccountName": f"{name}-csi-controller",
                    "hostNetwork": True,
                    "containers": [{
                        "name": "csi-driver", "image": image,
                        "securityContext": {"privileged": True},
                        "volumeMounts": [
                            {"name": "kubelet-dir",
                             "mountPath": "/var/lib/kubelet"}],
                    }],
                    "volumes": [{
                        "name": "kubelet-dir",
                        "hostPath": {"path": "/var/lib/kubelet"}}],
                },
            },
        },
    }
    return [sa, role, binding, ds]


@register("aws-efs-csi-driver", "EFS CSI driver + default PV/StorageClass "
                                "(kubeflow/aws efs parity)")
def aws_efs_csi_driver(namespace: str = "kubeflow",
                       filesystem_id: str = "",
                       storage_capacity: str = "100Gi") -> list[dict]:
    out = _csi_driver("efs", "docker.io/amazon/aws-efs-csi-driver:v0.2.0",
                      namespace)
    sc = {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
          "metadata": {"name": "efs-sc"},
          "provisioner": "efs.csi.aws.com"}
    out.append(sc)
    if filesystem_id:
        pv = k8s.make("v1", "PersistentVolume", "efs-pv")
        pv["spec"] = {
            "capacity": {"storage": storage_capacity},
            "accessModes": ["ReadWriteMany"],
            "persistentVolumeReclaimPolicy": "Retain",
            "storageClassName": "efs-sc",
            "csi": {"driver": "efs.csi.aws.com",
                    "volumeHandle": filesystem_id},
        }
        out.append(pv)
    return out


@register("aws-fsx-csi-driver", "FSx for Lustre CSI driver + StorageClass "
                                "(kubeflow/aws fsx parity)")
def aws_fsx_csi_driver(namespace: str = "kubeflow",
                       subnet_id: str = "",
                       security_group_id: str = "") -> list[dict]:
    out = _csi_driver("fsx", "docker.io/amazon/aws-fsx-csi-driver:v0.1.0",
                      namespace)
    sc = {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
          "metadata": {"name": "fsx-sc"},
          "provisioner": "fsx.csi.aws.com"}
    if subnet_id:
        sc["parameters"] = {"subnetId": subnet_id,
                            "securityGroupIds": security_group_id}
    out.append(sc)
    return out


@register("aws-istio-ingress", "Istio ingress gateway fronted by an ALB "
                               "(kubeflow/aws istio-ingress parity)")
def aws_istio_ingress(namespace: str = "kubeflow",
                      hostname: str = "*") -> list[dict]:
    ingress = {
        "apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
        "metadata": {
            "name": "istio-ingress", "namespace": namespace,
            "annotations": {
                "kubernetes.io/ingress.class": "alb",
                "alb.ingress.kubernetes.io/scheme": "internet-facing",
                "alb.ingress.kubernetes.io/listen-ports":
                    '[{"HTTP": 80}]',
            },
        },
        "spec": {"rules": [{
            "host": hostname if hostname != "*" else None,
            "http": {"paths": [{
                "path": "/", "pathType": "Prefix",
                "backend": {"service": {
                    "name": "istio-ingressgateway",
                    "port": {"number": 80}}}}]},
        }]},
    }
    if ingress["spec"]["rules"][0]["host"] is None:
        del ingress["spec"]["rules"][0]["host"]
    return [ingress]
