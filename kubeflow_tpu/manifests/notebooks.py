"""Notebook packages: Notebook CRD, controller, web app.

Reference: kubeflow/jupyter (notebooks.libsonnet CRD,
notebook_controller.libsonnet, jupyter-web-app.libsonnet; legacy JupyterHub
StatefulSet jupyter.libsonnet:128-150).
"""

from __future__ import annotations

from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
# per-image pin the auto-update bot retags independently of the
# module-wide VERSION (workflows/image_update.py)
JUPYTER_WEB_APP_VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"

# The CR wraps a full PodSpec (notebook_types.go:28-35 idiom — SURVEY §2.6).
_NOTEBOOK_SCHEMA = {
    "type": "object",
    "properties": {"spec": {
        "type": "object",
        "properties": {"template": {"type": "object"}},
    }},
}


@register("notebook-controller", "Notebook CRD + reconciler "
                                 "(components/notebook-controller parity)")
def notebook_controller(namespace: str = "kubeflow") -> list[dict]:
    nb_crd = H.crd("notebooks", "Notebook", "kubeflow.org", ["v1alpha1"],
                   schema=_NOTEBOOK_SCHEMA)
    sa = H.service_account("notebook-controller", namespace)
    role = H.cluster_role("notebook-controller", [
        {"apiGroups": ["kubeflow.org"], "resources": ["notebooks",
                                                      "notebooks/status"],
         "verbs": ["*"]},
        {"apiGroups": ["apps"], "resources": ["statefulsets"], "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["services", "pods", "events"],
         "verbs": ["*"]},
        {"apiGroups": ["networking.istio.io"],
         "resources": ["virtualservices"], "verbs": ["*"]},
    ])
    binding = H.cluster_role_binding("notebook-controller",
                                     "notebook-controller",
                                     "notebook-controller", namespace)
    dep = H.deployment("notebook-controller", namespace,
                       f"{IMG}/notebook-controller:{VERSION}",
                       service_account="notebook-controller",
                       env={"USE_ISTIO": "true"})
    return [nb_crd, sa, role, binding, dep]


@register("jupyter-web-app", "Notebook spawner web app "
                             "(components/jupyter-web-app parity)")
def jupyter_web_app(namespace: str = "kubeflow", ui: str = "default",
                    prefix: str = "jupyter") -> list[dict]:
    sa = H.service_account("jupyter-web-app", namespace)
    role = H.cluster_role("jupyter-web-app", [
        {"apiGroups": ["kubeflow.org"], "resources": ["notebooks",
                                                      "poddefaults"],
         "verbs": ["get", "list", "create", "delete"]},
        {"apiGroups": [""], "resources": ["persistentvolumeclaims",
                                          "namespaces", "secrets"],
         "verbs": ["get", "list", "create", "delete"]},
        {"apiGroups": ["storage.k8s.io"], "resources": ["storageclasses"],
         "verbs": ["get", "list"]},
    ])
    binding = H.cluster_role_binding("jupyter-web-app", "jupyter-web-app",
                                     "jupyter-web-app", namespace)
    spawner_cm = H.config_map("jupyter-web-app-config", namespace, {
        "ui": ui,
        # Default notebook images, incl. the TPU-ready image (the
        # tensorflow-notebook-image slot, components/tensorflow-notebook-image)
        "notebook-images": ",".join([
            f"{IMG}/jax-notebook-tpu:{VERSION}",
            f"{IMG}/jax-notebook-cpu:{VERSION}",
        ]),
        "default-tpu-topology": "v5e-1",
    })
    dep = H.deployment("jupyter-web-app", namespace,
                       f"{IMG}/jupyter-web-app:{JUPYTER_WEB_APP_VERSION}", port=5000,
                       service_account="jupyter-web-app",
                       env={"UI": ui, "URL_PREFIX": f"/{prefix}"})
    svc = H.service("jupyter-web-app", namespace, 80, target_port=5000)
    vs = H.virtual_service("jupyter-web-app", namespace, f"/{prefix}/",
                           "jupyter-web-app", 80)
    return [nb for nb in [sa, role, binding, spawner_cm, dep, svc, vs]]
