"""Serving packages: TPU model server, batch predict, tensorboard.

Reference packages: kubeflow/tf-serving (tf-serving.libsonnet: late-bound
params, deployment + gRPC/REST ports + HTTP proxy + HPA + platform mixins),
kubeflow/tf-batch-predict, kubeflow/tensorboard.
"""

from __future__ import annotations

import json

from ..api import k8s
from ..api.trainingjob import KF_API_VERSION_V1ALPHA1, TPU_API_VERSION
from . import helpers as H
from .registry import register

VERSION = "v0.1.0"
# per-image pin the auto-update bot retags independently (image_update.py)
MODEL_SERVER_VERSION = "v0.1.0"
IMG = "ghcr.io/kubeflow-tpu"


@register("tpu-serving", "TPU-backed model server (tf-serving.libsonnet parity: "
                         "gRPC+REST, HTTP proxy, HPA, storage params)")
def tpu_serving(namespace: str = "kubeflow", name: str = "model-server",
                model_path: str = "", model_name: str = "model",
                tpu_topology: str = "v5e-1", num_replicas: int = 1,
                enable_http_proxy: bool = True, enable_hpa: bool = False,
                hpa_min: int = 1, hpa_max: int = 4,
                reload_interval_s: int = 30,
                slo_p99_ms: float = None,
                slo_availability: float = None,
                max_pending: int = 0,
                drain_timeout_s: float = 10.0,
                batching: str = "continuous",
                max_wait_ms: float = None,
                autoscale: bool = False,
                autoscale_min: int = 1, autoscale_max: int = 4,
                autoscale_burn_threshold: float = 2.0,
                autoscale_queue_threshold: float = 4.0,
                autoscale_oldest_wait_s: float = 0.5,
                autoscale_idle_down_s: float = 300.0,
                autoscale_cooldown_s: float = 60.0) -> list[dict]:
    """``slo_p99_ms`` / ``slo_availability`` declare the model's SLO
    (serving/replica_state.py renders burn-rate gauges on /metrics);
    ``max_pending`` bounds the batcher queue — past it requests shed
    with 429 instead of queueing unbounded. ``num_replicas`` is the
    fleet size behind the Service; the resilience tier (ISSUE 12)
    rides on it: readiness probes on /healthz (flips 503 while
    draining so the endpoints controller routes away), liveness on
    /healthz?live=1 (stays 200 through a drain — the kubelet must not
    kill a gracefully-draining pod), a preStop httpGet /drain hook
    bounded by ``drain_timeout_s``, and — with 2+ replicas — a
    PodDisruptionBudget keeping N-1 available through voluntary
    disruptions.

    ``batching`` picks the micro-batcher's admission scheduler
    (ISSUE 18): ``continuous`` (in-flight batching, the default) or
    ``window`` (the legacy fixed collect window); ``max_wait_ms`` is
    continuous mode's idle-device coalescing bound. ``autoscale=True``
    emits a ``ServingFleet`` object carrying the ``autoscale_*``
    knobs — the ``autoscaler`` controller (controllers/autoscaler.py)
    reconciles it: scale-up onto warm pods on burn-rate/queue
    pressure, scale-down by graceful drain after sustained idle,
    with the cooldown as the flap guard."""
    from .observability import scrape_annotations
    lbl = {**H.std_labels(name), "kubeflow.org/servable": model_name}
    args = [f"--model-path={model_path}", f"--model-name={model_name}",
            "--grpc-port=9000", "--rest-port=8500",
            f"--reload-interval={reload_interval_s}",
            f"--drain-timeout={drain_timeout_s}",
            f"--batching={batching}"]
    if max_wait_ms is not None:
        args.append(f"--max-wait-ms={max_wait_ms}")
    if slo_p99_ms is not None:
        args.append(f"--slo-p99-ms={slo_p99_ms}")
    if slo_availability is not None:
        args.append(f"--slo-availability={slo_availability}")
    if max_pending:
        args.append(f"--max-pending={max_pending}")
    dep = H.deployment(
        name, namespace, f"{IMG}/tpu-model-server:{MODEL_SERVER_VERSION}",
        replicas=num_replicas, args=args,
        labels=lbl, port=9000,
        # the model server's /metrics rides the REST port
        pod_annotations=scrape_annotations(8500))
    pod_spec = dep["spec"]["template"]["spec"]
    serving_container = pod_spec["containers"][0]
    # readiness flips 503 the moment the replica starts draining;
    # liveness rides ?live=1 which stays 200 through the drain
    serving_container["readinessProbe"] = {
        "httpGet": {"path": "/healthz", "port": 8500},
        "periodSeconds": 5, "failureThreshold": 2,
    }
    serving_container["livenessProbe"] = {
        "httpGet": {"path": "/healthz?live=1", "port": 8500},
        "periodSeconds": 10, "failureThreshold": 3,
        "initialDelaySeconds": 10,
    }
    # preStop: the kubelet holds SIGTERM until the synchronous /drain
    # returns — in-flight work finishes, the batcher cohort flushes
    serving_container["lifecycle"] = {
        "preStop": {"httpGet": {"path": "/drain", "port": 8500}}}
    # pod teardown budget: the drain plus margin for the final flush
    pod_spec["terminationGracePeriodSeconds"] = \
        int(drain_timeout_s) + 20
    if model_path:
        # persistent XLA compile cache next to the model: replica
        # restarts and scale-ups skip the per-bucket warmup compiles
        # (runtime/compile_cache.py)
        from ..runtime.compile_cache import (COMPILE_CACHE_ENV,
                                             default_cache_dir)
        pod_spec["containers"][0].setdefault("env", []).append(
            {"name": COMPILE_CACHE_ENV,
             "value": default_cache_dir(model_path)})
    pod_spec["nodeSelector"] = {
        "cloud.google.com/gke-tpu-topology": tpu_topology}
    pod_spec["containers"][0]["resources"] = {
        "limits": {"google.com/tpu": 1}}
    pod_spec["containers"][0]["ports"] = [
        {"containerPort": 9000, "name": "grpc"},
        {"containerPort": 8500, "name": "rest"},
    ]
    if enable_http_proxy:
        pod_spec["containers"].append({
            "name": "http-proxy",
            "image": f"{IMG}/serving-http-proxy:{MODEL_SERVER_VERSION}",
            "args": ["--port=8000", "--rpc_timeout=10.0"],
            "ports": [{"containerPort": 8000, "name": "http"}],
        })
    svc = H.service(name, namespace, 9000, selector_name=name)
    svc["spec"]["ports"] = [
        {"port": 9000, "targetPort": 9000, "name": "grpc"},
        {"port": 8500, "targetPort": 8500, "name": "rest"},
        *([{"port": 8000, "targetPort": 8000, "name": "http"}]
          if enable_http_proxy else []),
    ]
    out = [dep, svc,
           H.virtual_service(name, namespace, f"/models/{model_name}/",
                             name, 8000 if enable_http_proxy else 8500)]
    if num_replicas >= 2:
        # voluntary disruptions (node drain, rollout) may take at most
        # one replica at a time — the kill-one-of-N soak's contract.
        # A single-replica deployment gets no PDB: minAvailable=1
        # there would block every drain forever.
        pdb = k8s.make("policy/v1", "PodDisruptionBudget", name,
                       namespace, labels=lbl)
        pdb["spec"] = {
            "minAvailable": num_replicas - 1,
            "selector": {"matchLabels": {H.APP_LABEL: name}},
        }
        out.append(pdb)
    if enable_hpa:
        hpa = k8s.make("autoscaling/v2", "HorizontalPodAutoscaler", name,
                       namespace)
        hpa["spec"] = {
            "scaleTargetRef": {"apiVersion": "apps/v1", "kind": "Deployment",
                               "name": name},
            "minReplicas": hpa_min, "maxReplicas": hpa_max,
            "metrics": [{"type": "Resource", "resource": {
                "name": "cpu",
                "target": {"type": "Utilization",
                           "averageUtilization": 80}}}],
        }
        out.append(hpa)
    if autoscale:
        # the metrics-driven serving autoscaler (ISSUE 18): unlike the
        # CPU-utilization HPA above, the ServingFleet scales on the
        # replica health registry's own signals (queue depth, oldest
        # wait, SLO burn rate) and actuates warm-pod add / graceful
        # drain through the autoscaler reconciler. Keys match
        # controllers/autoscaler.py AutoscalerConfig.KEYS.
        fleet = k8s.make(KF_API_VERSION_V1ALPHA1, "ServingFleet", name,
                         namespace, labels=lbl)
        fleet["spec"] = {
            "model": model_name,
            "service": name,
            "autoscaler": {
                "minReplicas": autoscale_min,
                "maxReplicas": autoscale_max,
                "burnUpThreshold": autoscale_burn_threshold,
                "queueUpThreshold": autoscale_queue_threshold,
                "oldestWaitUpSeconds": autoscale_oldest_wait_s,
                "idleDownSeconds": autoscale_idle_down_s,
                "cooldownSeconds": autoscale_cooldown_s,
            },
        }
        out.append(fleet)
    return out


@register("tpu-batch-predict", "Batch prediction Job on TPU "
                               "(kubeflow/tf-batch-predict parity)")
def tpu_batch_predict(namespace: str = "kubeflow", name: str = "batch-predict",
                      model_path: str = "", input_file_patterns: str = "",
                      output_result_prefix: str = "",
                      batch_size: int = 64,
                      tpu_topology: str = "v5e-1") -> list[dict]:
    job = k8s.make("batch/v1", "Job", name, namespace,
                   labels=H.std_labels(name))
    job["spec"] = {"template": {"spec": {
        "restartPolicy": "Never",
        "nodeSelector": {"cloud.google.com/gke-tpu-topology": tpu_topology},
        "containers": [{
            "name": name,
            "image": f"{IMG}/tpu-batch-predict:{VERSION}",
            "args": [f"--model-path={model_path}",
                     f"--input-file-patterns={input_file_patterns}",
                     f"--output-result-prefix={output_result_prefix}",
                     f"--batch-size={batch_size}"],
            "resources": {"limits": {"google.com/tpu": 1}},
        }],
    }}}
    return [job]


@register("tpu-serving-simple", "Example: serve the sample MNIST model on "
                                "one TPU chip (examples/prototypes/"
                                "tf-serving-simple.jsonnet analog)")
def tpu_serving_simple(namespace: str = "kubeflow",
                       name: str = "mnist-serving") -> list[dict]:
    """Canonical serving example: the smallest useful tpu-serving instance,
    pointed at the sample MNIST servable the batch-predict tests use. The
    reference's tf-serving-simple prototype is the same idea — tf-serving
    with an inception/mnist model and default everything."""
    return tpu_serving(namespace=namespace, name=name,
                       model_path="gs://kubeflow-tpu-examples/mnist/servable",
                       model_name="mnist", tpu_topology="v5e-1",
                       enable_http_proxy=True,
                       # the declarative SLO + bounded queue the serving
                       # observability plane tracks (ISSUE 11)
                       slo_p99_ms=250.0, slo_availability=0.999,
                       max_pending=256,
                       # the resilience tier (ISSUE 12): a 3-replica
                       # fleet with probes, preStop drain, and a PDB
                       num_replicas=3, drain_timeout_s=10.0)


@register("katib-studyjob-example", "Example StudyJob: random search over "
                                    "the ResNet-50 TPUJob's learning rate "
                                    "(katib-studyjob-test-v1alpha1.jsonnet "
                                    "analog)")
def katib_studyjob_example(namespace: str = "kubeflow",
                           name: str = "studyjob-example",
                           max_trials: int = 6,
                           request_number: int = 3) -> list[dict]:
    """Canonical HP-search example: a StudyJob whose trials are
    gang-scheduled TPUJobs, sweeping learning rate and per-chip batch size
    with the random suggestion engine. Field names follow the StudyJob
    schema reconciled by katib/studyjob.py."""
    study = k8s.make(KF_API_VERSION_V1ALPHA1, "StudyJob", name, namespace)
    study["spec"] = {
        "studyName": name,
        "owner": "crd",
        "optimizationtype": "maximize",
        "objectivevaluename": "accuracy",
        "metricsnames": ["accuracy", "loss"],
        "parameterconfigs": [
            {"name": "--learning-rate", "parametertype": "double",
             "feasible": {"min": "0.01", "max": "0.3"}},
            {"name": "--global-batch", "parametertype": "categorical",
             "feasible": {"list": ["512", "1024", "2048"]}},
        ],
        "suggestionSpec": {
            "suggestionAlgorithm": "random",
            "requestNumber": request_number,
        },
        "maxTrials": max_trials,
        "maxFailedTrials": 2,
        "workerSpec": {
            "injectParameters": True,
            "template": {
                "apiVersion": TPU_API_VERSION, "kind": "TPUJob",
                "metadata": {"name": "$(trialName)",
                             "namespace": namespace},
                "spec": {
                    "replicaSpecs": {"TPU": {
                        "tpuTopology": "v5e-8",
                        "template": {"spec": {"containers": [{
                            "name": "worker",
                            "image": f"{IMG}/worker:{VERSION}",
                            "command": [
                                "python", "-m",
                                "kubeflow_tpu.runtime.worker",
                                "--workload", "resnet50",
                                "--steps", "200"],
                        }]}},
                    }},
                    "runPolicy": {"backoffLimit": 1},
                    "sharding": {"data": -1},
                },
            },
        },
    }
    return [study]


@register("tensorboard", "TensorBoard deployment (kubeflow/tensorboard parity)")
def tensorboard(namespace: str = "kubeflow", name: str = "tensorboard",
                log_dir: str = "/logs") -> list[dict]:
    dep = H.deployment(name, namespace, f"{IMG}/tensorboard:{VERSION}",
                       args=[f"--logdir={log_dir}", "--port=6006"],
                       port=6006)
    svc = H.service(name, namespace, 80, target_port=6006)
    vs = H.virtual_service(name, namespace, f"/{name}/", name, 80)
    return [dep, svc, vs]


@register("serving-request-logger", "Request-log sidecar config for the "
                                    "model server (k8s-model-server/"
                                    "fluentd-logger parity)")
def serving_request_logger(namespace: str = "kubeflow",
                           serving_name: str = "tpu-serving",
                           log_path: str = "/var/log/serving/requests.log"
                           ) -> list[dict]:
    """Fluentd sidecar ConfigMap tailing the model server's request log
    into the cluster log pipeline; attach by adding the sidecar to the
    serving Deployment (the reference ships the same as a fluentd image +
    conf)."""
    conf = f"""<source>
  @type tail
  path {log_path}
  pos_file /var/log/serving/requests.pos
  tag serving.requests
  format json
</source>
<match serving.requests>
  @type stdout
</match>
"""
    cm = H.config_map(f"{serving_name}-request-logger", namespace,
                      {"fluent.conf": conf})
    sidecar = {
        "name": "request-logger",
        "image": "fluent/fluentd:v1.3-onbuild",
        "volumeMounts": [
            {"name": "request-log", "mountPath": "/var/log/serving"},
            {"name": "fluentd-conf", "mountPath": "/fluentd/etc"},
        ],
    }
    # the sidecar spec is published as data so installers can graft it
    # onto the serving pod template (the libsonnet mixin pattern)
    mixin = H.config_map(f"{serving_name}-request-logger-sidecar", namespace,
                         {"sidecar.json": json.dumps(sidecar)})
    return [cm, mixin]
