"""The GCP ingress/auth package: IAP ingress, basic-auth ingress,
cert-manager, cloud-endpoints, Filestore.

Reference: kubeflow/gcp/ (4.3k LoC jsonnet) — the largest reference package:
iap-ingress (Envoy verifying IAP JWTs, prototypes/iap-ingress.jsonnet:1-16),
basic-auth-ingress (gatekeeper-backed), cert-manager, cloud-endpoints,
Filestore PV, gpu-driver (covered by tpu-device-plugin in observability.py),
prometheus + metric-collector (observability.py).

The data-plane here is in-repo (webapps/ingress.py AuthIngress) rather than
an Envoy image: the Deployment below runs `python -m kubeflow_tpu.webapps
.ingress`-shaped entrypoints, so the manifests wire real code.
"""

from __future__ import annotations

from ..api import k8s
from . import helpers as H
from .registry import register

ESP_IMAGE = "kubeflow-tpu/auth-ingress:v0.1.0"  # webapps/ingress.py image


@register("iap-ingress", "IAP-style JWT-verifying ingress "
                         "(kubeflow/gcp/prototypes/iap-ingress parity)")
def iap_ingress(namespace: str = "kubeflow",
                hostname: str = "kubeflow.endpoints.example.cloud.goog",
                audience: str = "",
                ip_name: str = "kubeflow-ip",
                upstream: str = "centraldashboard:80") -> list[dict]:
    """Envoy-analog Deployment + config + GKE Ingress with a static IP.

    The audience is the IAP backend-service id the JWT must be minted
    for; the signing key arrives via the `iap-ingress-key` Secret (the
    reference pulls Google's public keys instead — same seam)."""
    cm = H.config_map("iap-ingress-config", namespace, {
        "audience": audience or "/projects/0/global/backendServices/0",
        "issuer": "https://cloud.google.com/iap",
        "upstream": upstream,
        "jwt_header": "x-goog-iap-jwt-assertion",
        "email_header": "x-goog-authenticated-user-email",
    })
    dep = H.deployment(
        "iap-ingress", namespace, ESP_IMAGE,
        args=["--mode=iap", "--config-dir=/etc/iap",
              "--key-file=/etc/iap-key/key", "--port=8080"],
        port=8080, replicas=2, service_account="iap-ingress")
    # mount config + signing-key secret like the reference's envoy pod
    pod = dep["spec"]["template"]["spec"]
    pod["volumes"] = [
        {"name": "config", "configMap": {"name": "iap-ingress-config"}},
        {"name": "key", "secret": {"secretName": "iap-ingress-key"}},
    ]
    pod["containers"][0]["volumeMounts"] = [
        {"name": "config", "mountPath": "/etc/iap"},
        {"name": "key", "mountPath": "/etc/iap-key", "readOnly": True},
    ]
    sa = H.service_account("iap-ingress", namespace)
    svc = H.service("iap-ingress", namespace, 80, target_port=8080)
    svc["metadata"].setdefault("annotations", {})[
        "beta.cloud.google.com/backend-config"] = \
        '{"default": "iap-backendconfig"}'
    backend_config = {
        "apiVersion": "cloud.google.com/v1", "kind": "BackendConfig",
        "metadata": {"name": "iap-backendconfig", "namespace": namespace},
        "spec": {"iap": {"enabled": True,
                         "oauthclientCredentials":
                             {"secretName": "iap-oauth-client"}}},
    }
    ingress = {
        "apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
        "metadata": {
            "name": "envoy-ingress", "namespace": namespace,
            "annotations": {
                "kubernetes.io/ingress.global-static-ip-name": ip_name,
                "networking.gke.io/managed-certificates": "kubeflow-cert",
            },
        },
        "spec": {"rules": [{
            "host": hostname,
            "http": {"paths": [{
                "path": "/", "pathType": "Prefix",
                "backend": {"service": {"name": "iap-ingress",
                                        "port": {"number": 80}}}}]},
        }]},
    }
    return [sa, cm, dep, svc, backend_config, ingress]


@register("basic-auth-ingress", "Gatekeeper-backed auth ingress "
                                "(kubeflow/gcp basic-auth flavor + "
                                "common/ambassador authservice parity)")
def basic_auth_ingress(namespace: str = "kubeflow",
                       hostname: str = "",
                       ip_name: str = "kubeflow-ip",
                       upstream: str = "centraldashboard:80") -> list[dict]:
    """AuthIngress in ext-authz mode in front of the gatekeeper: every
    request's Cookie/Authorization is checked against gatekeeper /auth;
    401 redirects to the login page (webapps/ingress.ExtAuthzVerifier)."""
    cm = H.config_map("basic-auth-ingress-config", namespace, {
        "auth_url": "http://gatekeeper:8085/auth",
        "login_path": "/login",
        "upstream": upstream,
    })
    dep = H.deployment(
        "basic-auth-ingress", namespace, ESP_IMAGE,
        args=["--mode=ext-authz", "--config-dir=/etc/auth-ingress",
              "--port=8080"],
        port=8080, replicas=2)
    pod = dep["spec"]["template"]["spec"]
    pod["volumes"] = [{"name": "config",
                       "configMap": {"name": "basic-auth-ingress-config"}}]
    pod["containers"][0]["volumeMounts"] = [
        {"name": "config", "mountPath": "/etc/auth-ingress"}]
    svc = H.service("basic-auth-ingress", namespace, 80, target_port=8080)
    ingress = {
        "apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
        "metadata": {
            "name": "basic-auth-ingress", "namespace": namespace,
            "annotations":
                {"kubernetes.io/ingress.global-static-ip-name": ip_name},
        },
        "spec": {"rules": [{
            **({"host": hostname} if hostname else {}),
            "http": {"paths": [{
                "path": "/", "pathType": "Prefix",
                "backend": {"service": {"name": "basic-auth-ingress",
                                        "port": {"number": 80}}}}]},
        }]},
    }
    return [cm, dep, svc, ingress]


@register("cert-manager", "Certificate/Issuer CRDs + controller + "
                          "self-signed default issuer "
                          "(kubeflow/gcp/cert-manager parity)")
def cert_manager(namespace: str = "cert-manager",
                 acme_email: str = "",
                 acme_server: str =
                 "https://acme-v02.api.letsencrypt.org/directory") -> list[dict]:
    ns = k8s.make("v1", "Namespace", namespace)
    crds = [
        H.crd("certificates", "Certificate", "certmanager.k8s.io",
              ["v1alpha1"]),
        H.crd("issuers", "Issuer", "certmanager.k8s.io", ["v1alpha1"]),
        H.crd("clusterissuers", "ClusterIssuer", "certmanager.k8s.io",
              ["v1alpha1"], scope="Cluster"),
    ]
    sa = H.service_account("cert-manager", namespace)
    role = H.cluster_role("cert-manager", [
        {"apiGroups": ["certmanager.k8s.io"],
         "resources": ["certificates", "issuers", "clusterissuers",
                       "certificates/status", "issuers/status"],
         "verbs": ["*"]},
        {"apiGroups": [""],
         "resources": ["secrets", "events", "services", "pods"],
         "verbs": ["get", "list", "watch", "create", "update", "delete"]},
        {"apiGroups": ["networking.k8s.io"], "resources": ["ingresses"],
         "verbs": ["get", "list", "watch", "create", "update", "delete"]},
    ])
    binding = H.cluster_role_binding("cert-manager", "cert-manager",
                                     "cert-manager", namespace)
    dep = H.deployment("cert-manager", namespace,
                       "quay.io/jetstack/cert-manager-controller:v0.4.0",
                       args=["--cluster-resource-namespace=" + namespace],
                       service_account="cert-manager", port=9402)
    issuer = {
        "apiVersion": "certmanager.k8s.io/v1alpha1", "kind": "ClusterIssuer",
        "metadata": {"name": "kubeflow-self-signing-issuer"},
        "spec": {"selfSigned": {}},
    }
    out = [ns, *crds, sa, role, binding, dep, issuer]
    if acme_email:
        out.append({
            "apiVersion": "certmanager.k8s.io/v1alpha1",
            "kind": "ClusterIssuer",
            "metadata": {"name": "letsencrypt-prod"},
            "spec": {"acme": {
                "email": acme_email, "server": acme_server,
                "privateKeySecretRef": {"name": "letsencrypt-prod-key"},
                "http01": {}}},
        })
    return out


@register("cloud-endpoints", "Cloud Endpoints DNS controller + "
                             "CloudEndpoint CRD (kubeflow/gcp parity)")
def cloud_endpoints(namespace: str = "kubeflow",
                    project: str = "") -> list[dict]:
    crd = H.crd("cloudendpoints", "CloudEndpoint", "ctl.isla.solutions",
                ["v1"])
    sa = H.service_account("cloud-endpoints-controller", namespace)
    role = H.cluster_role("cloud-endpoints-controller", [
        {"apiGroups": ["ctl.isla.solutions"], "resources": ["cloudendpoints"],
         "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["services", "configmaps"],
         "verbs": ["get", "list"]},
        {"apiGroups": ["networking.k8s.io"], "resources": ["ingresses"],
         "verbs": ["get", "list"]},
    ])
    binding = H.cluster_role_binding("cloud-endpoints-controller",
                                     "cloud-endpoints-controller",
                                     "cloud-endpoints-controller", namespace)
    dep = H.deployment("cloud-endpoints-controller", namespace,
                       "gcr.io/cloud-solutions-group/cloud-endpoints-controller:0.2.1",
                       service_account="cloud-endpoints-controller",
                       port=80, env={"GOOGLE_PROJECT": project} if project else None)
    return [crd, sa, role, binding, dep]


@register("gcp-filestore", "Filestore NFS PV/PVC for shared artifacts "
                           "(kubeflow/gcp filestore parity)")
def gcp_filestore(namespace: str = "kubeflow",
                  server_ip: str = "",
                  path: str = "/kubeflow",
                  capacity: str = "1Ti") -> list[dict]:
    pv = k8s.make("v1", "PersistentVolume", "kubeflow-filestore")
    pv["spec"] = {
        "capacity": {"storage": capacity},
        "accessModes": ["ReadWriteMany"],
        "persistentVolumeReclaimPolicy": "Retain",
        "nfs": {"server": server_ip or "10.0.0.2", "path": path},
    }
    pvc = k8s.make("v1", "PersistentVolumeClaim", "kubeflow-filestore",
                   namespace=namespace)
    pvc["spec"] = {
        "accessModes": ["ReadWriteMany"],
        "storageClassName": "",
        "volumeName": "kubeflow-filestore",
        "resources": {"requests": {"storage": capacity}},
    }
    return [pv, pvc]
