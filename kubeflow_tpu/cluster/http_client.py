"""HttpKubeClient — the real-cluster client.

Speaks the Kubernetes REST wire format (cluster/wire.py) against any
apiserver: a real one (via kubeconfig — server URL, CA bundle or
insecure-skip-tls-verify, bearer token / basic auth / client certs) or the
in-repo ClusterAPIServer. Everything in the framework that programs against
KubeClient — the controller Manager, the CLI apply path, the web apps —
runs unchanged over this client; reference parity:
bootstrap/pkg/kfapp/ksonnet/ksonnet.go:92-197 (apply against a live
apiserver), components/notebook-controller/.../notebook_controller.go:57-144
(watch wiring through client-go).

Watches are background threads reading chunked JSON-line streams
(GET ...?watch=true), with automatic reconnect. The server emits BOOKMARK
events for mutations a filtered stream does not match, so every stream
advances its resourceVersion high-water mark on every cluster mutation;
``wait_caught_up(rv)`` blocks until all streams have seen rv — giving the
same read-your-writes determinism tests get from the in-memory FakeCluster
(enabled via ``sync_watches=True``; off for production use).
"""

from __future__ import annotations

import json
import logging
import random
import ssl
import threading
import time
from typing import Optional
from urllib.parse import quote
from urllib.request import Request, urlopen

from . import wire
from .client import (AlreadyExistsError, ConflictError, KubeClient,
                     KubeError, NotFoundError, Watch, WatchEvent)

log = logging.getLogger(__name__)


def retry_after_s(headers) -> Optional[float]:
    """A server-sent Retry-After in seconds off a headers mapping, or
    None (numeric form only — the HTTP-date form is not worth a parser
    here; unparseable reads as absent). Shared by this client's bounded
    retry loop and the serving-side retry paths (serving/client.py,
    serving/fleet.py): a throttling server telling us when to come back
    must not be hammered at our own jitter cadence."""
    if headers is None:
        return None
    raw = headers.get("Retry-After")
    if raw is None:
        return None
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return None


def jittered_backoff(delay_s: float, rng=random) -> float:
    """One jittered backoff interval: uniform in [delay, 1.5*delay] —
    the decorrelation that keeps a fleet of retriers from hammering a
    recovering server in lockstep (thundering-herd protection)."""
    return delay_s * rng.uniform(1.0, 1.5)


class _HttpWatch(Watch):
    """A Watch fed by a background stream-reader thread."""

    def __init__(self, api_version: str, kind: str):
        super().__init__(api_version, kind)
        self.last_rv = 0  # high-water resourceVersion seen on this stream
        self.thread: Optional[threading.Thread] = None
        # set once the server-side subscription exists (initial bookmark
        # received); watch() blocks on it so a mutation issued right after
        # watch() returns can never race the subscription
        self.subscribed = threading.Event()

    def deliver(self, event: WatchEvent) -> None:  # no re-filtering needed
        if not self.closed:
            self.events.put(event)


class HttpKubeClient(KubeClient):
    def __init__(self, base_url: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None, insecure: bool = False,
                 client_cert: Optional[tuple[str, str]] = None,
                 basic_auth: Optional[tuple[str, str]] = None,
                 timeout: float = 30.0, sync_watches: bool = False,
                 retries: int = 3, retry_backoff_s: float = 0.2,
                 retry_wall_clock_s: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # transient-error budget: a 5xx / connection failure retries up to
        # `retries` times with exponential backoff + jitter before the
        # typed error surfaces — the controller must survive an apiserver
        # flake (LB blip, leader election, chaos-injected burst) without
        # burning its reconcile-retry budget. 4xx semantics (NotFound,
        # Conflict, AlreadyExists) are MEANING, not weather: never retried.
        # A throttling apiserver's Retry-After (429/503) is HONORED — a
        # server telling us when to come back must not be hammered at our
        # own jitter cadence during a health-event storm — and the total
        # sleep across one request's retries is capped at
        # `retry_wall_clock_s` so honoring it cannot pin a reconcile
        # worker for minutes.
        self.retries = max(0, int(retries))
        self.retry_backoff_s = retry_backoff_s
        self.retry_wall_clock_s = retry_wall_clock_s
        # read-your-writes barrier for deterministic drives (tests, CLI
        # apply-then-verify); production reconcilers are level-triggered and
        # don't need it
        self.sync_watches = sync_watches
        self._headers = {"Content-Type": "application/json",
                         "Accept": "application/json"}
        if token:
            self._headers["Authorization"] = f"Bearer {token}"
        elif basic_auth:
            import base64
            cred = base64.b64encode(
                f"{basic_auth[0]}:{basic_auth[1]}".encode()).decode()
            self._headers["Authorization"] = f"Basic {cred}"
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self.base_url.startswith("https"):
            self._ssl_ctx = ssl.create_default_context(cafile=ca_file)
            if insecure:
                self._ssl_ctx.check_hostname = False
                self._ssl_ctx.verify_mode = ssl.CERT_NONE
            if client_cert:
                self._ssl_ctx.load_cert_chain(client_cert[0], client_cert[1])
        self._watches: list[_HttpWatch] = []
        self._watch_lock = threading.Lock()

    # -- kubeconfig ----------------------------------------------------------

    @classmethod
    def from_kubeconfig(cls, path: str, context: Optional[str] = None,
                        **kw) -> "HttpKubeClient":
        """Build a client from a kubeconfig file (the subset kfctl and the
        manager need: clusters/users/contexts with token, basic-auth, or
        client-cert credentials)."""
        import yaml

        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
        clusters = {e["name"]: e.get("cluster", {})
                    for e in cfg.get("clusters", [])}
        users = {e["name"]: e.get("user", {}) for e in cfg.get("users", [])}
        contexts = {e["name"]: e.get("context", {})
                    for e in cfg.get("contexts", [])}
        ctx_name = context or cfg.get("current-context")
        if not ctx_name or ctx_name not in contexts:
            raise KubeError(f"kubeconfig {path}: no usable context "
                            f"({ctx_name!r})")
        ctx = contexts[ctx_name]
        cluster = clusters.get(ctx.get("cluster", ""), {})
        user = users.get(ctx.get("user", ""), {})
        server = cluster.get("server")
        if not server:
            raise KubeError(f"kubeconfig {path}: context {ctx_name!r} has "
                            "no cluster server")
        token = user.get("token")
        if not token and user.get("tokenFile"):
            with open(user["tokenFile"]) as f:
                token = f.read().strip()
        basic = None
        if user.get("username") and user.get("password"):
            basic = (user["username"], user["password"])
        client_cert = None
        if user.get("client-certificate") and user.get("client-key"):
            client_cert = (user["client-certificate"], user["client-key"])
        return cls(
            server, token=token, basic_auth=basic, client_cert=client_cert,
            ca_file=cluster.get("certificate-authority"),
            insecure=bool(cluster.get("insecure-skip-tls-verify")), **kw)

    # -- request plumbing ----------------------------------------------------

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        delay = self.retry_backoff_s
        slept = 0.0
        for attempt in range(self.retries + 1):
            req = Request(self.base_url + path, data=data,
                          headers=self._headers, method=method)
            try:
                with urlopen(req, timeout=self.timeout,
                             context=self._ssl_ctx) as resp:
                    return json.loads(resp.read() or b"{}")
            except Exception as e:
                payload = self._error_payload(e)
                if attempt < self.retries and self._is_transient(payload):
                    # jitter decorrelates a fleet of controllers hammering
                    # a recovering apiserver (thundering-herd protection);
                    # a server-sent Retry-After (429/503 throttling) wins
                    # over our own schedule — the server knows its load
                    sleep = jittered_backoff(delay)
                    retry_after = self._retry_after_s(e)
                    if retry_after is not None:
                        sleep = max(sleep, retry_after)
                    if slept + sleep > self.retry_wall_clock_s:
                        # wall-clock cap: honoring a long Retry-After (or
                        # stacking backoffs) must not pin this caller past
                        # the budget — surface the error, the reconcile
                        # loop's own requeue is the cheaper way to wait
                        log.warning("%s %s: retry budget exhausted "
                                    "(%.1fs slept, next wait %.1fs > "
                                    "%.1fs cap)", method, path, slept,
                                    sleep, self.retry_wall_clock_s)
                        raise self._typed_error(payload) from None
                    log.warning("%s %s transient (%s); retry %d/%d in "
                                "%.2fs", method, path,
                                payload.get("reason", "?"), attempt + 1,
                                self.retries, sleep)
                    time.sleep(sleep)
                    slept += sleep
                    delay *= 2
                    continue
                raise self._typed_error(payload) from None

    @staticmethod
    def _retry_after_s(e: Exception) -> Optional[float]:
        """The server's Retry-After in seconds, when the error carries
        one (the module-level retry_after_s over the error's headers)."""
        return retry_after_s(getattr(e, "headers", None))

    @staticmethod
    def _is_transient(payload: dict) -> bool:
        """5xx and connection-level failures (code 0: unreachable, timeout,
        dropped mid-response) are retryable weather; 4xx is meaning."""
        code = payload.get("code") or 0
        return code == 0 or code >= 500 or code == 429

    @staticmethod
    def _error_payload(e: Exception) -> dict:
        from urllib.error import HTTPError, URLError
        if isinstance(e, HTTPError):
            try:
                return json.loads(e.read() or b"{}")
            except Exception:  # noqa: BLE001 — non-JSON error body
                return wire.status_body(e.code, "Unknown", str(e))
        if isinstance(e, URLError):
            return wire.status_body(0, "Unreachable", str(e.reason))
        return wire.status_body(0, "ClientError", f"{type(e).__name__}: {e}")

    @staticmethod
    def _typed_error(status: dict) -> KubeError:
        reason = status.get("reason", "")
        message = status.get("message", json.dumps(status))
        if reason == "NotFound" or status.get("code") == 404:
            return NotFoundError(message)
        if reason == "AlreadyExists":
            return AlreadyExistsError(message)
        if reason == "Conflict":
            return ConflictError(message)
        return KubeError(f"{reason or 'Error'}: {message}")

    def _after_mutation(self, result: dict) -> dict:
        if self.sync_watches:
            rv = int(result.get("metadata", {}).get("resourceVersion", 0)
                     or 0)
            if rv:
                self.wait_caught_up(rv)
        return result

    # -- KubeClient surface --------------------------------------------------

    def create(self, obj: dict) -> dict:
        av, kind = obj.get("apiVersion", ""), obj.get("kind", "")
        ns = obj.get("metadata", {}).get("namespace")
        path = wire.collection_path(av, kind, ns)
        return self._after_mutation(self._request("POST", path, obj))

    def get(self, api_version: str, kind: str, namespace: str,
            name: str) -> dict:
        return self._request(
            "GET", wire.object_path(api_version, kind, namespace, name))

    def list(self, api_version: str, kind: str,
             namespace: Optional[str] = None,
             selector: Optional[dict] = None) -> list[dict]:
        path = wire.collection_path(api_version, kind, namespace)
        if selector:
            path += "?labelSelector=" + quote(wire.encode_selector(selector))
        return self._request("GET", path).get("items", [])

    def update(self, obj: dict) -> dict:
        av, kind = obj.get("apiVersion", ""), obj.get("kind", "")
        meta = obj.get("metadata", {})
        path = wire.object_path(av, kind, meta.get("namespace"),
                                meta.get("name", ""))
        return self._after_mutation(self._request("PUT", path, obj))

    def update_status(self, obj: dict) -> dict:
        av, kind = obj.get("apiVersion", ""), obj.get("kind", "")
        meta = obj.get("metadata", {})
        path = wire.object_path(av, kind, meta.get("namespace"),
                                meta.get("name", "")) + "/status"
        return self._after_mutation(self._request("PUT", path, obj))

    def patch(self, api_version: str, kind: str, namespace: str, name: str,
              patch: dict) -> dict:
        path = wire.object_path(api_version, kind, namespace, name)
        return self._after_mutation(self._request("PATCH", path, patch))

    def delete(self, api_version: str, kind: str, namespace: str, name: str,
               cascade: bool = True) -> None:
        path = wire.object_path(api_version, kind, namespace, name)
        if not cascade:
            path += "?propagationPolicy=Orphan"
        result = self._request("DELETE", path)
        if self.sync_watches:
            rv = (result.get("details") or {}).get("resourceVersion", "")
            if str(rv).isdigit():
                self.wait_caught_up(int(rv))

    # -- watch ---------------------------------------------------------------

    def watch(self, api_version: Optional[str] = None,
              kind: Optional[str] = None) -> Watch:
        if not api_version or not kind:
            raise KubeError("HttpKubeClient.watch requires api_version and "
                            "kind (a real apiserver has no watch-everything "
                            "endpoint)")
        w = _HttpWatch(api_version, kind)
        t = threading.Thread(target=self._stream_loop, args=(w,),
                             daemon=True,
                             name=f"watch-{kind}")
        w.thread = t
        with self._watch_lock:
            self._watches.append(w)
        t.start()
        # FakeCluster.watch subscribes synchronously; match that so
        # watch-then-mutate is race-free over the wire too
        w.subscribed.wait(timeout=self.timeout)
        return w

    def _stream_loop(self, w: _HttpWatch) -> None:
        import http.client as hc
        from urllib.parse import urlsplit

        split = urlsplit(self.base_url)
        path = wire.collection_path(w.api_version, w.kind) + "?watch=true"
        first_connect = True
        while not w.closed:
            conn = None
            try:
                if split.scheme == "https":
                    conn = hc.HTTPSConnection(split.hostname, split.port,
                                              context=self._ssl_ctx,
                                              timeout=self.timeout)
                else:
                    conn = hc.HTTPConnection(split.hostname, split.port,
                                             timeout=self.timeout)
                headers = {k: v for k, v in self._headers.items()
                           if k != "Content-Type"}
                conn.request("GET", path, headers=headers)
                resp = conn.getresponse()
                if resp.status != 200:
                    log.warning("watch %s: HTTP %s", w.kind, resp.status)
                    time.sleep(0.5)
                    continue
                if not first_connect:
                    # reconnect relist (informer resync analog): events in
                    # the connection gap were lost, so re-deliver current
                    # state as MODIFIED — level-triggered reconcilers just
                    # re-enqueue keys and read the store
                    try:
                        for obj in self.list(w.api_version, w.kind):
                            w.deliver(WatchEvent("MODIFIED", obj))
                    except KubeError as e:
                        log.warning("watch %s relist failed: %s", w.kind, e)
                first_connect = False
                # HTTPResponse.readline is chunk-decoding (io.BufferedIOBase
                # over read1); resp.fp would expose raw chunk framing
                while not w.closed:
                    line = resp.readline()
                    if not line:
                        break  # stream ended; reconnect
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    obj = ev.get("object", {})
                    rv = obj.get("metadata", {}).get("resourceVersion", "")
                    try:
                        w.last_rv = max(w.last_rv, int(rv))
                    except (TypeError, ValueError):
                        pass
                    # any line proves the server-side subscription exists
                    # (the server's initial bookmark arrives first)
                    w.subscribed.set()
                    if ev.get("type") != wire.BOOKMARK:
                        w.deliver(WatchEvent(ev.get("type", ""), obj))
            except OSError as e:
                if not w.closed:
                    log.debug("watch %s stream error: %s; reconnecting",
                              w.kind, e)
                    time.sleep(0.2)
            finally:
                if conn is not None:
                    conn.close()

    def wait_caught_up(self, rv: int, timeout: float = 10.0) -> bool:
        """Block until every open watch stream has seen resourceVersion
        >= rv (BOOKMARKs included). Used by sync_watches and by tests."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._watch_lock:
                self._watches = [w for w in self._watches if not w.closed]
                behind = [w for w in self._watches if w.last_rv < rv]
            if not behind:
                return True
            time.sleep(0.002)
        return False

    def close(self) -> None:
        with self._watch_lock:
            for w in self._watches:
                w.close()
            self._watches.clear()
