"""Leader election over coordination.k8s.io-style Lease objects.

The reference platform's controllers are kubebuilder reconcilers that run
``replicas: 2`` behind client-go leader election as a matter of course; our
operator and scheduler were single processes — a crashed controller manager
took the whole control plane's write path with it until a human restarted
it. This module is the HA substrate:

- **The Lease wire contract.** One Lease object per controller deployment
  (``coordination.k8s.io/v1`` Lease on the same apiserver everything else
  uses). Field names are defined HERE and only here — the elector, the
  soaks, the dashboard's control-plane panel, and the manifests all
  consume these constants (the ``binding_of`` single-definition rule,
  pinned by tests/test_lint.py).
- **Acquire / renew / steal.** ``try_acquire`` is one optimistic-
  concurrency round: read the lease, and create (absent), renew (ours),
  or steal (expired) — every write carries the read's resourceVersion as
  a precondition, so two replicas racing for the same expiry produce
  exactly one winner; the loser's update 409s and it stays a follower.
- **Fencing.** ``leaseTransitions`` is the fencing token: it increments
  on every change of holder. A leader that cannot renew within the lease
  duration demotes ITSELF (its local clock is enough — the classic
  client-go rule), and ``FencedKubeClient`` rejects every mutating call
  from a demoted/never-elected replica before it reaches the wire. The
  split-brain drill (scheduler/soak.py) proves the window: partition the
  leader, let a standby steal, and the old leader's writes raise
  ``FencingError`` instead of doubling pod creates.

jax-free, like the rest of cluster/.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from .client import (AlreadyExistsError, ConflictError, KubeClient,
                     KubeError, NotFoundError, Watch)

log = logging.getLogger(__name__)

# ---------------------------------------------------------------- the wire
# THE one definition of the Lease object contract (test_lint.py pins these
# literals to this module; everyone else imports).

LEASE_API_VERSION = "coordination.k8s.io/v1"
LEASE_KIND = "Lease"
# spec field names (the coordination.k8s.io shapes; times are unix floats
# here — the simulated apiserver is schema-free and floats keep the
# expiry arithmetic exact)
HOLDER_FIELD = "holderIdentity"
ACQUIRE_TIME_FIELD = "acquireTime"
RENEW_TIME_FIELD = "renewTime"
DURATION_FIELD = "leaseDurationSeconds"
# the fencing token: bumped exactly once per change of holder, so any
# consumer can order "who held this lease when" without trusting clocks
TRANSITIONS_FIELD = "leaseTransitions"

# default lease homes (the manifests render these through to the
# controller CLI; tests/test_lint.py checks the plumbing)
DEFAULT_LEASE_NAMESPACE = "kubeflow"
OPERATOR_LEASE = "tpu-job-operator"
SCHEDULER_LEASE = "tpu-scheduler"


class FencingError(KubeError):
    """A mutating call from a replica that does not (or no longer does)
    hold its lease. Raised CLIENT-side before the write reaches the
    apiserver: a deposed leader must not race its successor."""


@dataclass
class LeaseRecord:
    """Parsed view of one Lease object's spec."""

    holder: str = ""
    acquire_time: float = 0.0
    renew_time: float = 0.0
    duration_s: float = 15.0
    transitions: int = 0

    def expired(self, now: float) -> bool:
        """Whether the current holder's claim has lapsed (no holder
        counts as expired — the lease is free)."""
        if not self.holder:
            return True
        return now - self.renew_time > self.duration_s


def lease_record(obj: Optional[dict]) -> LeaseRecord:
    """Parse a Lease object; zeros/empty when absent or malformed (a
    garbage lease reads as free — stealing it is safe because the write
    still carries the rv precondition)."""
    spec = (obj or {}).get("spec") or {}
    try:
        return LeaseRecord(
            holder=str(spec.get(HOLDER_FIELD, "") or ""),
            acquire_time=float(spec.get(ACQUIRE_TIME_FIELD, 0.0) or 0.0),
            renew_time=float(spec.get(RENEW_TIME_FIELD, 0.0) or 0.0),
            duration_s=float(spec.get(DURATION_FIELD, 15.0) or 15.0),
            transitions=int(spec.get(TRANSITIONS_FIELD, 0) or 0))
    except (TypeError, ValueError):
        return LeaseRecord()


def _lease_obj(namespace: str, name: str, rec: LeaseRecord) -> dict:
    return {
        "apiVersion": LEASE_API_VERSION, "kind": LEASE_KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            HOLDER_FIELD: rec.holder,
            ACQUIRE_TIME_FIELD: rec.acquire_time,
            RENEW_TIME_FIELD: rec.renew_time,
            DURATION_FIELD: rec.duration_s,
            TRANSITIONS_FIELD: rec.transitions,
        },
    }


@dataclass
class AcquireResult:
    acquired: bool
    record: LeaseRecord
    # why the attempt did not acquire ("held", "lost-race", "error")
    reason: str = ""


def try_acquire(client: KubeClient, namespace: str, name: str,
                identity: str, duration_s: float,
                now: Optional[float] = None) -> AcquireResult:
    """One conflict-safe acquire/renew round. Exactly one of N
    concurrent callers wins any given transition: every write carries
    the resourceVersion of the read it was computed from, so a
    concurrent steal 409s the loser (who returns acquired=False and
    keeps following)."""
    now = time.time() if now is None else now
    existing = None
    try:
        existing = client.get(LEASE_API_VERSION, LEASE_KIND, namespace,
                              name)
    except NotFoundError:
        pass
    if existing is None:
        rec = LeaseRecord(holder=identity, acquire_time=now,
                          renew_time=now, duration_s=duration_s,
                          transitions=1)
        try:
            client.create(_lease_obj(namespace, name, rec))
            return AcquireResult(True, rec)
        except (AlreadyExistsError, ConflictError):
            return AcquireResult(False, rec, "lost-race")
    rec = lease_record(existing)
    if rec.holder == identity:
        new = LeaseRecord(holder=identity, acquire_time=rec.acquire_time,
                          renew_time=now, duration_s=duration_s,
                          transitions=rec.transitions)
    elif rec.expired(now):
        # steal: the holder's claim lapsed — the transition bumps the
        # fencing token so the old holder's token goes stale
        new = LeaseRecord(holder=identity, acquire_time=now,
                          renew_time=now, duration_s=duration_s,
                          transitions=rec.transitions + 1)
    else:
        return AcquireResult(False, rec, "held")
    obj = _lease_obj(namespace, name, new)
    obj["metadata"]["resourceVersion"] = \
        existing["metadata"].get("resourceVersion")
    try:
        client.update(obj)
        return AcquireResult(True, new)
    except ConflictError:
        return AcquireResult(False, rec, "lost-race")


def release(client: KubeClient, namespace: str, name: str,
            identity: str) -> bool:
    """Graceful release: clear the holder so a successor acquires on its
    NEXT attempt instead of waiting out the full lease duration.
    Conflict-safe — a lease already stolen from us is left alone."""
    try:
        existing = client.get(LEASE_API_VERSION, LEASE_KIND, namespace,
                              name)
    except (NotFoundError, KubeError):
        return False
    rec = lease_record(existing)
    if rec.holder != identity:
        return False
    new = LeaseRecord(holder="", acquire_time=rec.acquire_time,
                      renew_time=0.0, duration_s=rec.duration_s,
                      transitions=rec.transitions)
    obj = _lease_obj(namespace, name, new)
    obj["metadata"]["resourceVersion"] = \
        existing["metadata"].get("resourceVersion")
    try:
        client.update(obj)
        return True
    except (ConflictError, KubeError):
        return False


# ---------------------------------------------------------------- elector


@dataclass
class LeaderElector:
    """The per-replica election loop state. ``ensure()`` is called from
    the hosting controller loop (controllers/runtime.py gates
    process_one on it): it acquires/renews at ``renew_every_s`` cadence
    and answers "am I the leader RIGHT NOW" off the local clock —
    a leader that has not managed a successful renew within the lease
    duration is NOT the leader anymore, whatever it last read, because
    a standby may already have stolen the lease (the partition-safety
    rule client-go leader election follows).
    """

    client: KubeClient
    identity: str
    name: str
    namespace: str = DEFAULT_LEASE_NAMESPACE
    duration_s: float = 15.0
    # renew cadence; defaults to duration/3 when 0 (the client-go ratio)
    renew_every_s: float = 0.0
    clock: object = time.time

    _held: bool = field(default=False, repr=False)
    _last_renew_ok: float = field(default=0.0, repr=False)
    _next_attempt: float = field(default=0.0, repr=False)
    _token: int = field(default=0, repr=False)
    _was_leader: bool = field(default=False, repr=False)
    _transitions_seen: int = field(default=0, repr=False)

    def __post_init__(self):
        if not self.renew_every_s:
            self.renew_every_s = max(self.duration_s / 3.0, 0.01)

    # -- state ----------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        """Local-clock leadership: held AND renewed recently enough.
        This is the check FencedKubeClient makes per mutating call —
        no apiserver round trip, and safe under partition: once the
        lease duration passes without a successful renew, a standby may
        hold the lease, so the answer must be False."""
        return self._held and \
            (self.clock() - self._last_renew_ok) <= self.duration_s

    @property
    def token(self) -> int:
        """The fencing token of our CURRENT claim (leaseTransitions at
        acquire); stale once someone else acquires."""
        return self._token

    # -- the loop hook ---------------------------------------------------

    def ensure(self, now: Optional[float] = None) -> bool:
        """Acquire or renew when due; returns is_leader. Errors (an
        apiserver partition, a chaos burst) never raise — they just
        mean no successful renew, and local expiry demotes us."""
        now = self.clock() if now is None else now
        if now < self._next_attempt:
            return self.is_leader
        self._next_attempt = now + self.renew_every_s
        try:
            res = try_acquire(self.client, self.namespace, self.name,
                              self.identity, self.duration_s, now=now)
        except Exception as e:  # noqa: BLE001 — election must not crash
            log.warning("lease %s/%s: acquire attempt failed for %s: %s",
                        self.namespace, self.name, self.identity, e)
            res = AcquireResult(False, LeaseRecord(), "error")
        if res.acquired:
            self._held = True
            self._last_renew_ok = now
            self._token = res.record.transitions
        else:
            self._held = False
        self._observe(res)
        return self.is_leader

    def _observe(self, res: AcquireResult) -> None:
        from ..obs import registry as obsreg
        leader = self.is_leader
        obsreg.gauge(
            "kftpu_leader",
            "1 while this replica holds its controller lease",
            labels=("lease", "identity")).labels(
                lease=self.name, identity=self.identity).set(
                    1 if leader else 0)
        transitions = res.record.transitions
        if transitions > self._transitions_seen:
            if self._transitions_seen:
                obsreg.counter(
                    "kftpu_lease_transitions_total",
                    "observed changes of lease holder (failovers)",
                    labels=("lease",)).labels(lease=self.name).inc(
                        transitions - self._transitions_seen)
            self._transitions_seen = transitions
        if leader and not self._was_leader:
            log.info("lease %s/%s: %s became leader (token %d)",
                     self.namespace, self.name, self.identity,
                     self._token)
        elif self._was_leader and not leader:
            log.warning("lease %s/%s: %s lost leadership",
                        self.namespace, self.name, self.identity)
        self._was_leader = leader

    def release(self) -> bool:
        """Graceful handoff (shutdown path): clear the lease so the
        standby takes over immediately instead of waiting out the
        duration."""
        self._held = False
        self._observe(AcquireResult(False, LeaseRecord()))
        return release(self.client, self.namespace, self.name,
                       self.identity)


# ----------------------------------------------------------- fenced client


# the KubeClient mutating surface (reads and watches pass unfenced —
# "non-leaders watch but do not write")
MUTATING_OPS = ("create", "update", "update_status", "patch", "delete")


class FencedKubeClient(KubeClient):
    """KubeClient wrapper that rejects mutating calls unless its elector
    currently holds the lease. The enforcement boundary for
    "non-leaders watch but do not write": even if a gating bug let a
    follower's reconcile run, its writes die HERE, client-side, before
    they can race the real leader's. Reads, lists, and watches pass
    through — a hot standby keeps its caches warm."""

    def __init__(self, inner: KubeClient, elector: LeaderElector):
        self.inner = inner
        self.elector = elector
        # fenced-write attempts rejected (the split-brain drill's
        # acceptance number rides on this being observable)
        self.rejected = 0
        self._lock = threading.Lock()

    def _fence(self, op: str, detail: str) -> None:
        if not self.elector.is_leader:
            with self._lock:
                self.rejected += 1
            raise FencingError(
                f"fenced: {self.elector.identity} is not the leader of "
                f"{self.elector.namespace}/{self.elector.name}; "
                f"refusing {op} {detail}")

    # -- mutating surface -------------------------------------------------

    def create(self, obj: dict) -> dict:
        self._fence("create", obj.get("kind", "?"))
        return self.inner.create(obj)

    def update(self, obj: dict) -> dict:
        self._fence("update", obj.get("kind", "?"))
        return self.inner.update(obj)

    def update_status(self, obj: dict) -> dict:
        self._fence("update_status", obj.get("kind", "?"))
        return self.inner.update_status(obj)

    def patch(self, api_version: str, kind: str, namespace: str,
              name: str, patch: dict) -> dict:
        self._fence("patch", f"{kind}/{name}")
        return self.inner.patch(api_version, kind, namespace, name, patch)

    def delete(self, api_version: str, kind: str, namespace: str,
               name: str, cascade: bool = True) -> None:
        self._fence("delete", f"{kind}/{name}")
        return self.inner.delete(api_version, kind, namespace, name,
                                 cascade=cascade)

    # -- read surface -----------------------------------------------------

    def get(self, api_version: str, kind: str, namespace: str,
            name: str) -> dict:
        return self.inner.get(api_version, kind, namespace, name)

    def list(self, api_version: str, kind: str, namespace=None,
             selector=None) -> list[dict]:
        return self.inner.list(api_version, kind, namespace, selector)

    def watch(self, api_version: Optional[str] = None,
              kind: Optional[str] = None) -> Watch:
        return self.inner.watch(api_version, kind)

    def __getattr__(self, name):
        # test-driver helpers (tick, fail_pod, ...) are the harness's
        # hand, not controller traffic — unfenced, like ChaosKubeClient
        return getattr(self.inner, name)
