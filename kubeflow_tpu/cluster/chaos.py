"""Fault injection for the control plane and the recovery paths it guards.

SURVEY §5's failure contract ("a dead worker kills the gang",
checkpoint-resume makes gang restarts cheap) is only as good as the
recovery code nobody exercises: checkpoint writes interrupted mid-flight,
restart pacing under a preemption storm, hung-but-not-dead workers, flaky
apiservers. This module makes those scenarios first-class and repeatable:

- **ChaosKubeClient** wraps any KubeClient (FakeCluster or the HTTP
  client) and injects deterministic, seeded faults at the client surface:
  transient 5xx-style errors (``TransientAPIError``) on a per-call budget
  or an explicit burst, and watch-stream drops. Controllers under test run
  against the wrapper unmodified; the test's own "hand of god" helpers
  (tick, fail_pod, ...) pass through un-faulted.
- **Checkpoint corruptors** (`truncate_checkpoint_payload`,
  `uncommit_checkpoint`) produce exactly the on-disk states a writer dying
  mid-save leaves behind, so restore-side integrity checking
  (runtime/checkpoint.py) is testable without racing a real kill.
- **ChaosSoak** drives one TPUJob end-to-end on the in-memory cluster,
  running REAL training segments in-process between scripted faults, and
  reports whether the job still converged to Succeeded with the params an
  uninjected run produces. Used by ``bench.py --mode chaos`` and the
  ``-m chaos`` test tier.

Layering: this module is jax-free at import time (like the rest of
cluster/ — the operator process must not pull in jax); ChaosSoak imports
the worker runtime lazily inside run().
"""

from __future__ import annotations

import logging
import os
import random
import time
from dataclasses import dataclass, field
from typing import Optional

from ..api import k8s
from .client import KubeClient, KubeError, Watch

log = logging.getLogger(__name__)


class TransientAPIError(KubeError):
    """An injected transient failure: the 5xx / connection-timeout class a
    real apiserver emits under load. Retryable by contract — controllers
    and the HTTP client must survive a bounded burst of these."""


# the client ops eligible for injection (the KubeClient surface)
CHAOS_OPS = ("create", "get", "list", "update", "update_status", "patch",
             "delete")

# On-disk markers of a committed checkpoint step (mirrors
# runtime/checkpoint.py, which cannot be imported here: it pulls in jax
# at module scope and cluster/ must stay jax-free).
ORBAX_COMMIT_MARKER = "_CHECKPOINT_METADATA"
MANIFEST_NAME = "kftpu.manifest.json"


@dataclass
class ChaosPolicy:
    """Seeded background fault schedule for ChaosKubeClient.

    ``error_rate`` injects a TransientAPIError on that fraction of eligible
    calls (seeded — the same seed replays the same fault positions);
    ``max_errors`` bounds the total so a soak always makes progress.
    Explicit bursts (``fail_next``) ride on top and ignore the budget.
    """

    seed: int = 0
    error_rate: float = 0.0
    max_errors: int = 0          # 0 = no rate-based injection
    ops: tuple = CHAOS_OPS


@dataclass
class InjectedFault:
    op: str
    detail: str
    at_call: int
    kind: str = "api-error"


class ChaosKubeClient(KubeClient):
    """KubeClient wrapper injecting seeded transient faults.

    Helper attributes not on the KubeClient surface (FakeCluster's tick,
    fail_pod, add_tpu_slice_nodes, ...) delegate to the inner client
    UN-faulted: they are the test driver's hand, not controller traffic.
    """

    def __init__(self, inner: KubeClient,
                 policy: Optional[ChaosPolicy] = None):
        self.inner = inner
        self.policy = policy or ChaosPolicy()
        self._rng = random.Random(self.policy.seed)
        self._burst = 0
        self._rate_injected = 0
        self.calls = 0
        self.injected: list[InjectedFault] = []
        self._live_watches: list[Watch] = []

    # ----------------------------------------------------------- injection

    def fail_next(self, n: int = 1) -> None:
        """Arm an explicit burst: the next n eligible calls raise
        TransientAPIError (an apiserver 5xx burst / brief outage)."""
        self._burst += int(n)

    def _maybe_fail(self, op: str, detail: str) -> None:
        self.calls += 1
        if op not in self.policy.ops:
            return
        if self._burst > 0:
            self._burst -= 1
            self.injected.append(InjectedFault(op, detail, self.calls))
            raise TransientAPIError(
                f"injected 5xx: {op} {detail} (burst)")
        if (self.policy.error_rate > 0
                and self._rate_injected < self.policy.max_errors
                and self._rng.random() < self.policy.error_rate):
            self._rate_injected += 1
            self.injected.append(InjectedFault(op, detail, self.calls))
            raise TransientAPIError(
                f"injected 5xx: {op} {detail} "
                f"({self._rate_injected}/{self.policy.max_errors})")

    # ------------------------------------------------- KubeClient surface

    def create(self, obj: dict) -> dict:
        self._maybe_fail("create", k8s.name_of(obj))
        return self.inner.create(obj)

    def get(self, api_version: str, kind: str, namespace: str,
            name: str) -> dict:
        self._maybe_fail("get", f"{kind}/{name}")
        return self.inner.get(api_version, kind, namespace, name)

    def list(self, api_version: str, kind: str, namespace=None,
             selector=None) -> list[dict]:
        self._maybe_fail("list", kind)
        return self.inner.list(api_version, kind, namespace, selector)

    def update(self, obj: dict) -> dict:
        self._maybe_fail("update", k8s.name_of(obj))
        return self.inner.update(obj)

    def update_status(self, obj: dict) -> dict:
        self._maybe_fail("update_status", k8s.name_of(obj))
        return self.inner.update_status(obj)

    def patch(self, api_version: str, kind: str, namespace: str, name: str,
              patch: dict) -> dict:
        self._maybe_fail("patch", f"{kind}/{name}")
        return self.inner.patch(api_version, kind, namespace, name, patch)

    def delete(self, api_version: str, kind: str, namespace: str, name: str,
               cascade: bool = True) -> None:
        self._maybe_fail("delete", f"{kind}/{name}")
        return self.inner.delete(api_version, kind, namespace, name,
                                 cascade=cascade)

    def watch(self, api_version=None, kind=None) -> Watch:
        w = self.inner.watch(api_version, kind)
        self._live_watches.append(w)
        return w

    def drop_watch_streams(self) -> int:
        """Close every watch opened through this client — the mid-run
        stream drop a flaky apiserver/LB produces. FakeCluster watches do
        not reconnect, so recovery must come from the controller's
        periodic resync (controllers/runtime.py resync_interval)."""
        dropped = 0
        for w in self._live_watches:
            if not w.closed:
                w.close()
                dropped += 1
        self.injected.append(InjectedFault(
            "watch", f"dropped {dropped} streams", self.calls,
            kind="watch-drop"))
        return dropped

    def __getattr__(self, name):
        # FakeCluster test helpers (tick, fail_pod, set_pod_phase, ...)
        return getattr(self.inner, name)


# ------------------------------------------------------ checkpoint faults


def latest_step_dir(directory: str) -> Optional[str]:
    """Newest integer-named step dir, committed or not — the raw view a
    corruptor targets (restore-side code must NOT use this)."""
    try:
        steps = sorted(int(n) for n in os.listdir(directory)
                       if n.isdigit()
                       and os.path.isdir(os.path.join(directory, n)))
    except OSError:
        return None
    return os.path.join(directory, str(steps[-1])) if steps else None


def truncate_checkpoint_payload(step_dir: str, keep_frac: float = 0.5
                                ) -> str:
    """Truncate the largest payload file in a committed step dir — the
    state a node dying mid-write (or a partial object PUT) leaves behind.
    The commit marker stays, so only content verification (the checksum
    manifest) can catch it. Returns the truncated path."""
    candidates = []
    for root, _dirs, files in os.walk(step_dir):
        for fname in files:
            if fname in (MANIFEST_NAME, ORBAX_COMMIT_MARKER):
                continue
            path = os.path.join(root, fname)
            candidates.append((os.path.getsize(path), path))
    if not candidates:
        raise FileNotFoundError(f"no payload files under {step_dir}")
    size, path = max(candidates)
    with open(path, "r+b") as f:
        f.truncate(max(1, int(size * keep_frac)))
    log.info("chaos: truncated %s to %d/%d bytes", path,
             max(1, int(size * keep_frac)), size)
    return path


def uncommit_checkpoint(step_dir: str) -> None:
    """Remove the orbax commit marker — the state a writer dying between
    directory rename and metadata finalize leaves behind. latest_step()
    must skip such a step entirely."""
    marker = os.path.join(step_dir, ORBAX_COMMIT_MARKER)
    if os.path.exists(marker):
        os.remove(marker)


# ------------------------------------------------- host-pinned faults


@dataclass
class HostFault:
    """A RECURRING fault pinned to one host — the failure class the
    node-health subsystem (scheduler/health.py) exists for. Unlike the
    one-shot SoakFault menu below, a HostFault keeps firing at pods
    scheduled onto its node until its ``trips`` budget runs out: a
    flaky host crash-loops every gang placed on it, however many times
    the operator restarts the gang — only migrating OFF the host (the
    suspect/quarantine path) or exhausting the budget (the host
    "recovers") ends the loop.

    Modes:
    - ``crash``: fail the pod (kubelet OOM-kill / device wedge class);
    - ``stall``: freeze the pod's heartbeat annotation ``stall_by_s``
      in the past (hung-but-not-dead worker — only a per-worker stall
      watchdog sees it);
    - ``skew``: advertise a heartbeat step ``skew_steps`` behind
      (slow-host step inflation: the pod is alive and beating but its
      steps lag the gang — the straggler signal).
    """

    node: str
    mode: str = "crash"
    trips: int = 3
    stall_by_s: float = 60.0
    skew_steps: int = 10
    fired: int = 0

    MODES = ("crash", "stall", "skew")

    def __post_init__(self):
        if self.mode not in self.MODES:
            raise ValueError(f"unknown HostFault mode {self.mode!r} "
                             f"(choose from {self.MODES})")

    def target_pods(self, cluster, namespace: str) -> list[dict]:
        """Running pods currently scheduled onto the pinned host."""
        return sorted(
            (p for p in cluster.list("v1", "Pod", namespace)
             if p.get("spec", {}).get("nodeName") == self.node
             and p.get("status", {}).get("phase") == "Running"),
            key=k8s.name_of)

    def maybe_fire(self, cluster, namespace: str,
                   at_step: int = 0) -> Optional[str]:
        """Fire at the first Running pod on the host, if any and the
        trips budget allows; returns the victim pod name."""
        if self.fired >= self.trips:
            return None
        pods = self.target_pods(cluster, namespace)
        if not pods:
            return None
        victim = k8s.name_of(pods[0])
        self.fired += 1
        if self.mode == "crash":
            cluster.fail_pod(namespace, victim,
                             f"chaos: flaky host {self.node}")
        else:
            import json as _json

            from ..api.trainingjob import HEARTBEAT_ANNOTATION
            if self.mode == "stall":
                payload = {"step": at_step,
                           "time": time.time() - self.stall_by_s}
            else:   # skew: alive and beating, steps lagging
                payload = {"step": max(0, at_step - self.skew_steps),
                           "time": time.time()}
            cluster.patch("v1", "Pod", namespace, victim, {
                "metadata": {"annotations": {
                    HEARTBEAT_ANNOTATION: _json.dumps(payload)}}})
        log.info("chaos: host fault %s/%s on %s (trip %d/%d)",
                 self.mode, victim, self.node, self.fired, self.trips)
        return victim


@dataclass
class CapacityLoss:
    """A host VANISHING from the cluster mid-run: the Node OBJECT is
    deleted (hypervisor death, node-pool scale-down, zone reclaim) —
    not merely flapped NotReady. The inventory then has no node
    claiming that host's cells, so they carve out as down and any
    binding covering them invalidates: the failure class elastic
    shrink-to-survive exists for (scheduler/core.py — a gang with no
    same-size rectangle left re-binds DEGRADED instead of starving).
    ``restore()`` re-creates the node (capacity returns: spare stock,
    pool scale-up), which is what grow-to-fill recovers into."""

    node: str
    fired: bool = False
    _saved: Optional[dict] = field(default=None, repr=False)

    def fire(self, cluster) -> bool:
        """Delete the node object; remembers it for restore()."""
        import copy
        node = cluster.get_or_none("v1", "Node", "", self.node)
        if node is None:
            return False
        self._saved = copy.deepcopy(node)
        cluster.delete("v1", "Node", "", self.node)
        self.fired = True
        log.info("chaos: capacity loss — node %s vanished", self.node)
        return True

    def restore(self, cluster) -> bool:
        """Bring the host back (fresh object identity, same name/labels
        — a replacement machine, not a resurrection)."""
        import copy
        if self._saved is None:
            return False
        obj = copy.deepcopy(self._saved)
        for stale in ("uid", "resourceVersion", "creationTimestamp"):
            obj.get("metadata", {}).pop(stale, None)
        cluster.create(obj)
        self._saved = None
        log.info("chaos: capacity restored — node %s is back", self.node)
        return True


# ---------------------------------------------------------------- the soak


# fault kinds the soak can inject between training segments
SOAK_FAULT_KINDS = ("pod-kill", "pod-fail", "api-burst", "watch-drop",
                    "truncate-ckpt", "hung-chief")


@dataclass
class SoakFault:
    """Inject `kind` once training has reached `at_step` global steps."""

    at_step: int
    kind: str

    def __post_init__(self):
        if self.kind not in SOAK_FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(choose from {SOAK_FAULT_KINDS})")


@dataclass
class ChaosSoak:
    """Drive one TPUJob through a scripted fault sequence, end to end.

    The control plane is real (FakeCluster + scheduler + the TPUJob
    reconciler, over a ChaosKubeClient); the data plane is real too — each
    time the gang is fully Running, a REAL training segment
    (runtime/worker.train, tiny transformer on the CPU mesh) runs
    in-process using the env the operator rendered into the chief pod
    (KFTPU_CHECKPOINT_DIR / KFTPU_RESUME_FROM), up to the next scripted
    fault's step. Faults then hit the cluster, the controller recovers
    (gang restart + resume), and the loop continues until the job reaches
    ``total_steps`` and the chief succeeds.

    Determinism: state.rng is checkpointed and the synthetic batch pool is
    seed-derived, so replayed steps recompute identical params — the
    report's final params must match an uninjected run bit-for-bit up to
    float tolerance (bench asserts ≤1e-5).
    """

    workdir: str
    faults: list = field(default_factory=list)
    total_steps: int = 6
    checkpoint_every: int = 2
    seed: int = 0
    global_batch: int = 8
    stall_timeout_s: int = 30
    restart_backoff_s: float = 0.02
    restart_backoff_max_s: float = 0.2
    wall_budget_s: float = 300.0
    namespace: str = "kubeflow"
    job_name: str = "chaos-soak"

    def _manifest(self, ckpt_dir: str) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": self.job_name,
                         "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {
                    "backoffLimit": len(self.faults) + 3,
                    "restartBackoffSeconds": self.restart_backoff_s,
                    "restartBackoffMaxSeconds": self.restart_backoff_max_s,
                    "stallTimeoutSeconds": self.stall_timeout_s,
                },
            },
        }

    def _chief_env(self, cluster, chief: str) -> dict:
        pod = cluster.get("v1", "Pod", self.namespace, chief)
        return {e["name"]: e.get("value", "")
                for e in pod["spec"]["containers"][0].get("env", [])}

    def _run_segment(self, env_map: dict, target: int):
        from ..obs.trace import adopt_trace_env
        from ..runtime.worker import train  # lazy: pulls in jax
        # adopt the operator-rendered trace contract for the segment:
        # the in-process "worker" reads the SAME env a real pod would,
        # so its window/ckpt spans stitch onto the job's trace id and
        # the goodput ledger can account the whole soak (ISSUE 10)
        with adopt_trace_env(env_map):
            return train(
                workload="transformer", steps=target,
                global_batch=self.global_batch, sync_every=1,
                checkpoint_dir=env_map.get("KFTPU_CHECKPOINT_DIR"),
                checkpoint_every=self.checkpoint_every,
                resume_from=env_map.get("KFTPU_RESUME_FROM"),
                seed=self.seed, handle_sigterm=False, workload_kwargs={})

    def _heartbeat(self, cluster, chief: str, step: int,
                   stale_by_s: float = 0.0) -> None:
        import json as _json
        from ..api.trainingjob import HEARTBEAT_ANNOTATION
        payload = _json.dumps({"step": step,
                               "time": time.time() - stale_by_s})
        cluster.patch("v1", "Pod", self.namespace, chief,
                      {"metadata": {"annotations":
                                    {HEARTBEAT_ANNOTATION: payload}}})

    def _inject(self, fault: SoakFault, cluster, chaos: ChaosKubeClient,
                ckpt_dir: str, chief: str, step: int) -> None:
        log.info("chaos soak: injecting %s at step %d", fault.kind, step)
        worker_pods = sorted(
            k8s.name_of(p)
            for p in cluster.list("v1", "Pod", self.namespace))
        victim = worker_pods[-1] if worker_pods else chief
        if fault.kind == "pod-kill":
            # preemption deletes the pod OBJECT (no Failed phase): the
            # vanish detector must gang-restart
            cluster.delete("v1", "Pod", self.namespace, victim)
        elif fault.kind == "pod-fail":
            cluster.fail_pod(self.namespace, victim, "chaos: worker died")
        elif fault.kind == "api-burst":
            # a 5xx burst right as the gang fails: reconcile attempts hit
            # injected errors and must retry through them
            chaos.fail_next(3)
            cluster.fail_pod(self.namespace, victim, "chaos: worker died")
        elif fault.kind == "watch-drop":
            chaos.drop_watch_streams()
            cluster.fail_pod(self.namespace, victim, "chaos: worker died")
        elif fault.kind == "truncate-ckpt":
            step_dir = latest_step_dir(ckpt_dir)
            if step_dir:
                truncate_checkpoint_payload(step_dir)
            cluster.fail_pod(self.namespace, victim, "chaos: worker died")
        elif fault.kind == "hung-chief":
            # live pod, stale heartbeat: only the stall watchdog recovers
            self._heartbeat(cluster, chief, step,
                            stale_by_s=self.stall_timeout_s + 5)

    def run(self) -> dict:
        from ..controllers.runtime import Manager
        from ..controllers.tpujob import (RESTART_COUNT_ANNOTATION,
                                          TrainingJobReconciler)
        from .fake import FakeCluster

        ckpt_dir = os.path.join(self.workdir, "ckpt")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        chaos = ChaosKubeClient(cluster)
        mgr = Manager(chaos)
        ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
        # watch-drop recovery depends on the periodic resync; keep it tight
        # so the soak converges quickly
        ctrl.resync_interval = 0.02
        cluster.create(self._manifest(ckpt_dir))

        pending = sorted((SoakFault(f.at_step, f.kind) if
                          not isinstance(f, SoakFault) else f
                          for f in self.faults), key=lambda f: f.at_step)
        report: dict = {"injected": [], "restart_reasons": [],
                        "segments": 0, "executed_steps": 0,
                        "outcome": "timeout"}
        deadline = time.monotonic() + self.wall_budget_s
        chief = f"{self.job_name}-worker-0-0"
        reached = 0
        while time.monotonic() < deadline:
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            job = cluster.get_or_none("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                      self.namespace, self.job_name)
            if job is None:
                report["outcome"] = "deleted"
                break
            cond = k8s.get_condition(job, "Restarting")
            if cond is not None and cond.get("status") == "True" and \
                    cond.get("reason") not in report["restart_reasons"]:
                report["restart_reasons"].append(cond.get("reason"))
            if k8s.condition_true(job, "Succeeded"):
                report["outcome"] = "succeeded"
                break
            if k8s.condition_true(job, "Failed"):
                report["outcome"] = "failed"
                report["failed_reason"] = k8s.get_condition(
                    job, "Failed").get("reason")
                break
            pods = cluster.list("v1", "Pod", self.namespace)
            running = [p for p in pods
                       if p.get("status", {}).get("phase") == "Running"]
            if len(running) != 2 or k8s.condition_true(job, "Restarting"):
                # gang down or mid-restart: let timers (restart backoff,
                # resync) fire and reconcile again
                time.sleep(0.03)
                continue
            target = min(pending[0].at_step, self.total_steps) if pending \
                else self.total_steps
            result = self._run_segment(self._chief_env(cluster, chief),
                                       target)
            report["segments"] += 1
            # steps this segment actually EXECUTED (its windows): the
            # soak's ground truth for restart-recompute — executed
            # minus final progress = steps replayed after restores,
            # which the goodput ledger must reproduce from spans alone
            report["executed_steps"] += int(result.steps)
            reached = max(reached, target)
            self._heartbeat(cluster, chief, reached)
            if pending and pending[0].at_step <= reached:
                fault = pending.pop(0)
                report["injected"].append({"step": reached,
                                           "kind": fault.kind})
                self._inject(fault, cluster, chaos, ckpt_dir, chief,
                             reached)
                continue
            if reached >= self.total_steps:
                # training done: the chief exits 0 and the operator
                # completes the job off the Succeeded phase
                cluster.set_pod_phase(self.namespace, chief, "Succeeded")
        job = cluster.get_or_none("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                  self.namespace, self.job_name)
        if job is not None:
            report["gang_restarts"] = int(k8s.annotations_of(job).get(
                RESTART_COUNT_ANNOTATION, "0"))
            from ..obs.trace import TRACE_ID_ANNOTATION
            report["trace_id"] = k8s.annotations_of(job).get(
                TRACE_ID_ANNOTATION, "")
        report["final_step"] = reached
        report["checkpoint_dir"] = ckpt_dir
        report["api_calls"] = chaos.calls
        report["api_faults"] = len(chaos.injected)
        for c in mgr.controllers:
            c.stop()
        return report


def final_params(checkpoint_dir: str):
    """Restore the params tree at the newest INTACT step (the integrity
    path — corrupted steps fall back). jax/orbax import is lazy."""
    from ..runtime.checkpoint import CheckpointManager
    mgr = CheckpointManager(checkpoint_dir)
    try:
        return mgr.restore_params()
    finally:
        mgr.close()
