"""Fault injection for the control plane and the recovery paths it guards.

SURVEY §5's failure contract ("a dead worker kills the gang",
checkpoint-resume makes gang restarts cheap) is only as good as the
recovery code nobody exercises: checkpoint writes interrupted mid-flight,
restart pacing under a preemption storm, hung-but-not-dead workers, flaky
apiservers. This module makes those scenarios first-class and repeatable:

- **ChaosKubeClient** wraps any KubeClient (FakeCluster or the HTTP
  client) and injects deterministic, seeded faults at the client surface:
  transient 5xx-style errors (``TransientAPIError``) on a per-call budget
  or an explicit burst, and watch-stream drops. Controllers under test run
  against the wrapper unmodified; the test's own "hand of god" helpers
  (tick, fail_pod, ...) pass through un-faulted.
- **Checkpoint corruptors** (`truncate_checkpoint_payload`,
  `uncommit_checkpoint`) produce exactly the on-disk states a writer dying
  mid-save leaves behind, so restore-side integrity checking
  (runtime/checkpoint.py) is testable without racing a real kill.
- **ChaosSoak** drives one TPUJob end-to-end on the in-memory cluster,
  running REAL training segments in-process between scripted faults, and
  reports whether the job still converged to Succeeded with the params an
  uninjected run produces. Used by ``bench.py --mode chaos`` and the
  ``-m chaos`` test tier.

Layering: this module is jax-free at import time (like the rest of
cluster/ — the operator process must not pull in jax); ChaosSoak imports
the worker runtime lazily inside run().
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..api import k8s
from .client import KubeClient, KubeError, Watch

log = logging.getLogger(__name__)


class TransientAPIError(KubeError):
    """An injected transient failure: the 5xx / connection-timeout class a
    real apiserver emits under load. Retryable by contract — controllers
    and the HTTP client must survive a bounded burst of these."""


# the client ops eligible for injection (the KubeClient surface)
CHAOS_OPS = ("create", "get", "list", "update", "update_status", "patch",
             "delete")

# On-disk markers of a committed checkpoint step (mirrors
# runtime/checkpoint.py, which cannot be imported here: it pulls in jax
# at module scope and cluster/ must stay jax-free).
ORBAX_COMMIT_MARKER = "_CHECKPOINT_METADATA"
MANIFEST_NAME = "kftpu.manifest.json"


@dataclass
class ChaosPolicy:
    """Seeded background fault schedule for ChaosKubeClient.

    ``error_rate`` injects a TransientAPIError on that fraction of eligible
    calls (seeded — the same seed replays the same fault positions);
    ``max_errors`` bounds the total so a soak always makes progress.
    Explicit bursts (``fail_next``) ride on top and ignore the budget.
    """

    seed: int = 0
    error_rate: float = 0.0
    max_errors: int = 0          # 0 = no rate-based injection
    ops: tuple = CHAOS_OPS


@dataclass
class InjectedFault:
    op: str
    detail: str
    at_call: int
    kind: str = "api-error"


class ChaosKubeClient(KubeClient):
    """KubeClient wrapper injecting seeded transient faults.

    Helper attributes not on the KubeClient surface (FakeCluster's tick,
    fail_pod, add_tpu_slice_nodes, ...) delegate to the inner client
    UN-faulted: they are the test driver's hand, not controller traffic.
    """

    def __init__(self, inner: KubeClient,
                 policy: Optional[ChaosPolicy] = None):
        self.inner = inner
        self.policy = policy or ChaosPolicy()
        self._rng = random.Random(self.policy.seed)
        self._burst = 0
        self._rate_injected = 0
        self.calls = 0
        self.injected: list[InjectedFault] = []
        self._live_watches: list[Watch] = []

    # ----------------------------------------------------------- injection

    def fail_next(self, n: int = 1) -> None:
        """Arm an explicit burst: the next n eligible calls raise
        TransientAPIError (an apiserver 5xx burst / brief outage)."""
        self._burst += int(n)

    def _maybe_fail(self, op: str, detail: str) -> None:
        self.calls += 1
        if op not in self.policy.ops:
            return
        if self._burst > 0:
            self._burst -= 1
            self.injected.append(InjectedFault(op, detail, self.calls))
            raise TransientAPIError(
                f"injected 5xx: {op} {detail} (burst)")
        if (self.policy.error_rate > 0
                and self._rate_injected < self.policy.max_errors
                and self._rng.random() < self.policy.error_rate):
            self._rate_injected += 1
            self.injected.append(InjectedFault(op, detail, self.calls))
            raise TransientAPIError(
                f"injected 5xx: {op} {detail} "
                f"({self._rate_injected}/{self.policy.max_errors})")

    # ------------------------------------------------- KubeClient surface

    def create(self, obj: dict) -> dict:
        self._maybe_fail("create", k8s.name_of(obj))
        return self.inner.create(obj)

    def get(self, api_version: str, kind: str, namespace: str,
            name: str) -> dict:
        self._maybe_fail("get", f"{kind}/{name}")
        return self.inner.get(api_version, kind, namespace, name)

    def list(self, api_version: str, kind: str, namespace=None,
             selector=None) -> list[dict]:
        self._maybe_fail("list", kind)
        return self.inner.list(api_version, kind, namespace, selector)

    def update(self, obj: dict) -> dict:
        self._maybe_fail("update", k8s.name_of(obj))
        return self.inner.update(obj)

    def update_status(self, obj: dict) -> dict:
        self._maybe_fail("update_status", k8s.name_of(obj))
        return self.inner.update_status(obj)

    def patch(self, api_version: str, kind: str, namespace: str, name: str,
              patch: dict) -> dict:
        self._maybe_fail("patch", f"{kind}/{name}")
        return self.inner.patch(api_version, kind, namespace, name, patch)

    def delete(self, api_version: str, kind: str, namespace: str, name: str,
               cascade: bool = True) -> None:
        self._maybe_fail("delete", f"{kind}/{name}")
        return self.inner.delete(api_version, kind, namespace, name,
                                 cascade=cascade)

    def watch(self, api_version=None, kind=None) -> Watch:
        w = self.inner.watch(api_version, kind)
        self._live_watches.append(w)
        return w

    def drop_watch_streams(self) -> int:
        """Close every watch opened through this client — the mid-run
        stream drop a flaky apiserver/LB produces. FakeCluster watches do
        not reconnect, so recovery must come from the controller's
        periodic resync (controllers/runtime.py resync_interval)."""
        dropped = 0
        for w in self._live_watches:
            if not w.closed:
                w.close()
                dropped += 1
        self.injected.append(InjectedFault(
            "watch", f"dropped {dropped} streams", self.calls,
            kind="watch-drop"))
        return dropped

    def __getattr__(self, name):
        # FakeCluster test helpers (tick, fail_pod, set_pod_phase, ...)
        return getattr(self.inner, name)


# ------------------------------------------------- control-plane faults
# The ControllerChaos arm (ISSUE 14): faults against the CONTROL PLANE
# itself — the one component the chaos harness had never killed. A
# controller process dying is not a 5xx: its in-memory state (queues,
# retry counts, first-seen maps) evaporates while its half-finished
# writes stay in the cluster. These are the seeded kill-points that
# produce exactly those states.


class ControllerCrash(KubeError):
    """The controller process died. Raised AFTER the triggering write
    landed (the write is on the wire when the process is killed), and
    on every call thereafter — a dead process has no connection."""


# controller-chaos fault kinds (scheduler/soak.py ControlPlaneSoak menu)
CTRL_FAULT_KINDS = ("kill-operator", "kill-scheduler",
                    "apiserver-partition", "stale-watch-rewind")


class ControllerChaos(ChaosKubeClient):
    """ChaosKubeClient plus the control-plane fault menu:

    - ``die_after(op, n)`` — the controller is killed immediately AFTER
      its nth matching call SUCCEEDS: the write persisted, the process
      did not. ``die_after("create", 2)`` kills the operator mid-gang-
      create (service + first pod landed, rest of the gang never
      created); arming it on the scheduler right before a bind kills it
      between the binding write and the operator's pod creates.
    - ``partition(seconds)`` — every call (reads included) raises
      TransientAPIError until the deadline: the apiserver is on the
      other side of a network split. Leases cannot renew through it, so
      a partitioned leader demotes itself (cluster/lease.py).
    - ``rewind_watch()`` — re-delivers the current state of every object
      as MODIFIED events carrying a STALE resourceVersion into the live
      watch streams (a reconnecting informer replaying history).
      Level-triggered reconcilers must re-read and no-op.
    - ``kill()`` / ``revive()`` — hard process death: every subsequent
      call raises ControllerCrash until revived (a killed replica's
      client object may leak into scheduled work; it must never write).
    """

    def __init__(self, inner: KubeClient,
                 policy: Optional[ChaosPolicy] = None):
        super().__init__(inner, policy)
        self.dead = False
        self._die_arm: Optional[tuple] = None   # (op, remaining)
        self._partition_until = 0.0

    # ------------------------------------------------------------ arming

    def die_after(self, op: str, n: int = 1) -> None:
        self._die_arm = (op, int(n))

    def partition(self, seconds: float) -> None:
        self._partition_until = time.monotonic() + seconds
        self.injected.append(InjectedFault(
            "partition", f"{seconds:.2f}s", self.calls,
            kind="apiserver-partition"))

    @property
    def partitioned(self) -> bool:
        return time.monotonic() < self._partition_until

    def kill(self) -> None:
        self.dead = True

    def revive(self) -> None:
        self.dead = False
        self._die_arm = None

    def rewind_watch(self) -> int:
        """Replay every object as a stale-rv MODIFIED event into the
        live watches (the stale-watch-rewind fault). Returns events
        delivered."""
        import copy as _copy

        from .client import MODIFIED as _MOD
        from .client import WatchEvent as _WE
        delivered = 0
        # the driver's hand: read current state through the inner client
        # (no fault injection), stamp a stale rv, replay into the streams
        for obj in list(getattr(self.inner, "_objects", {}).values()):
            stale = _copy.deepcopy(obj)
            stale.setdefault("metadata", {})["resourceVersion"] = "1"
            for w in self._live_watches:
                if not w.closed and w.matches(stale):
                    w.deliver(_WE(_MOD, stale))
                    delivered += 1
        self.injected.append(InjectedFault(
            "watch", f"rewound {delivered} events", self.calls,
            kind="stale-watch-rewind"))
        return delivered

    # --------------------------------------------------------- injection

    def _maybe_fail(self, op: str, detail: str) -> None:
        if self.dead:
            raise ControllerCrash(f"controller is dead ({op} {detail})")
        if self.partitioned:
            self.calls += 1
            raise TransientAPIError(
                f"injected partition: {op} {detail}")
        super()._maybe_fail(op, detail)

    def _maybe_die(self, op: str, kind: str = "") -> None:
        if self._die_arm is None:
            return
        if kind == "Lease":
            # the elector shares this connection: a kill-point armed on
            # the controller's writes must not fire on a lease renewal
            # (renews happen every duration/3 — they would win the race
            # to the armed death nearly every time, and the mid-write
            # window the soak exists to exercise would go untested)
            return
        armed_op, remaining = self._die_arm
        if op != armed_op:
            return
        remaining -= 1
        if remaining > 0:
            self._die_arm = (armed_op, remaining)
            return
        self._die_arm = None
        self.dead = True
        self.injected.append(InjectedFault(
            op, "controller killed after this call landed", self.calls,
            kind="controller-crash"))
        raise ControllerCrash(
            f"controller killed right after {op} landed")

    # kill-point wrapping: the inner call SUCCEEDS first, then the
    # process "dies" — exactly the crash-consistency window

    def create(self, obj: dict) -> dict:
        out = super().create(obj)
        self._maybe_die("create", obj.get("kind", ""))
        return out

    def update(self, obj: dict) -> dict:
        out = super().update(obj)
        self._maybe_die("update", obj.get("kind", ""))
        return out

    def update_status(self, obj: dict) -> dict:
        out = super().update_status(obj)
        self._maybe_die("update_status", obj.get("kind", ""))
        return out

    def patch(self, api_version: str, kind: str, namespace: str,
              name: str, patch: dict) -> dict:
        out = super().patch(api_version, kind, namespace, name, patch)
        self._maybe_die("patch", kind)
        return out

    def delete(self, api_version: str, kind: str, namespace: str,
               name: str, cascade: bool = True) -> None:
        out = super().delete(api_version, kind, namespace, name,
                             cascade=cascade)
        self._maybe_die("delete", kind)
        return out


class RecordingKubeClient(KubeClient):
    """KubeClient wrapper recording every MUTATING call that passes
    through it — the audit layer the HA acceptance criteria ride on
    ("non-leader processes provably make zero mutating calls").
    ``ignore_kinds`` excludes the election mechanism itself (Lease
    renewals are how a standby stays a standby, not controller
    writes)."""

    def __init__(self, inner: KubeClient,
                 ignore_kinds: tuple = ("Lease",)):
        self.inner = inner
        self.ignore_kinds = tuple(ignore_kinds)
        self.mutations: list[tuple] = []   # (op, kind, namespace, name)
        self._lock = threading.Lock()

    def _note(self, op: str, kind: str, namespace: str,
              name: str) -> None:
        if kind in self.ignore_kinds:
            return
        with self._lock:
            self.mutations.append((op, kind, namespace, name))

    def create(self, obj: dict) -> dict:
        self._note("create", obj.get("kind", ""),
                   k8s.namespace_of(obj, ""), k8s.name_of(obj))
        return self.inner.create(obj)

    def update(self, obj: dict) -> dict:
        self._note("update", obj.get("kind", ""),
                   k8s.namespace_of(obj, ""), k8s.name_of(obj))
        return self.inner.update(obj)

    def update_status(self, obj: dict) -> dict:
        self._note("update_status", obj.get("kind", ""),
                   k8s.namespace_of(obj, ""), k8s.name_of(obj))
        return self.inner.update_status(obj)

    def patch(self, api_version: str, kind: str, namespace: str,
              name: str, patch: dict) -> dict:
        self._note("patch", kind, namespace, name)
        return self.inner.patch(api_version, kind, namespace, name, patch)

    def delete(self, api_version: str, kind: str, namespace: str,
               name: str, cascade: bool = True) -> None:
        self._note("delete", kind, namespace, name)
        return self.inner.delete(api_version, kind, namespace, name,
                                 cascade=cascade)

    def get(self, api_version: str, kind: str, namespace: str,
            name: str) -> dict:
        return self.inner.get(api_version, kind, namespace, name)

    def list(self, api_version: str, kind: str, namespace=None,
             selector=None) -> list[dict]:
        return self.inner.list(api_version, kind, namespace, selector)

    def watch(self, api_version=None, kind=None) -> Watch:
        return self.inner.watch(api_version, kind)

    def __getattr__(self, name):
        return getattr(self.inner, name)


# ------------------------------------------------------ checkpoint faults


def latest_step_dir(directory: str) -> Optional[str]:
    """Newest integer-named step dir, committed or not — the raw view a
    corruptor targets (restore-side code must NOT use this)."""
    try:
        steps = sorted(int(n) for n in os.listdir(directory)
                       if n.isdigit()
                       and os.path.isdir(os.path.join(directory, n)))
    except OSError:
        return None
    return os.path.join(directory, str(steps[-1])) if steps else None


def truncate_checkpoint_payload(step_dir: str, keep_frac: float = 0.5
                                ) -> str:
    """Truncate the largest payload file in a committed step dir — the
    state a node dying mid-write (or a partial object PUT) leaves behind.
    The commit marker stays, so only content verification (the checksum
    manifest) can catch it. Returns the truncated path."""
    candidates = []
    for root, _dirs, files in os.walk(step_dir):
        for fname in files:
            if fname in (MANIFEST_NAME, ORBAX_COMMIT_MARKER):
                continue
            path = os.path.join(root, fname)
            candidates.append((os.path.getsize(path), path))
    if not candidates:
        raise FileNotFoundError(f"no payload files under {step_dir}")
    size, path = max(candidates)
    with open(path, "r+b") as f:
        f.truncate(max(1, int(size * keep_frac)))
    log.info("chaos: truncated %s to %d/%d bytes", path,
             max(1, int(size * keep_frac)), size)
    return path


def uncommit_checkpoint(step_dir: str) -> None:
    """Remove the orbax commit marker — the state a writer dying between
    directory rename and metadata finalize leaves behind. latest_step()
    must skip such a step entirely."""
    marker = os.path.join(step_dir, ORBAX_COMMIT_MARKER)
    if os.path.exists(marker):
        os.remove(marker)


# ------------------------------------------------- host-pinned faults


@dataclass
class HostFault:
    """A RECURRING fault pinned to one host — the failure class the
    node-health subsystem (scheduler/health.py) exists for. Unlike the
    one-shot SoakFault menu below, a HostFault keeps firing at pods
    scheduled onto its node until its ``trips`` budget runs out: a
    flaky host crash-loops every gang placed on it, however many times
    the operator restarts the gang — only migrating OFF the host (the
    suspect/quarantine path) or exhausting the budget (the host
    "recovers") ends the loop.

    Modes:
    - ``crash``: fail the pod (kubelet OOM-kill / device wedge class);
    - ``stall``: freeze the pod's heartbeat annotation ``stall_by_s``
      in the past (hung-but-not-dead worker — only a per-worker stall
      watchdog sees it);
    - ``skew``: advertise a heartbeat step ``skew_steps`` behind
      (slow-host step inflation: the pod is alive and beating but its
      steps lag the gang — the straggler signal).
    """

    node: str
    mode: str = "crash"
    trips: int = 3
    stall_by_s: float = 60.0
    skew_steps: int = 10
    fired: int = 0

    MODES = ("crash", "stall", "skew")

    def __post_init__(self):
        if self.mode not in self.MODES:
            raise ValueError(f"unknown HostFault mode {self.mode!r} "
                             f"(choose from {self.MODES})")

    def target_pods(self, cluster, namespace: str) -> list[dict]:
        """Running pods currently scheduled onto the pinned host."""
        return sorted(
            (p for p in cluster.list("v1", "Pod", namespace)
             if p.get("spec", {}).get("nodeName") == self.node
             and p.get("status", {}).get("phase") == "Running"),
            key=k8s.name_of)

    def maybe_fire(self, cluster, namespace: str,
                   at_step: int = 0) -> Optional[str]:
        """Fire at the first Running pod on the host, if any and the
        trips budget allows; returns the victim pod name."""
        if self.fired >= self.trips:
            return None
        pods = self.target_pods(cluster, namespace)
        if not pods:
            return None
        victim = k8s.name_of(pods[0])
        self.fired += 1
        if self.mode == "crash":
            cluster.fail_pod(namespace, victim,
                             f"chaos: flaky host {self.node}")
        else:
            import json as _json

            from ..api.trainingjob import HEARTBEAT_ANNOTATION
            if self.mode == "stall":
                payload = {"step": at_step,
                           "time": time.time() - self.stall_by_s}
            else:   # skew: alive and beating, steps lagging
                payload = {"step": max(0, at_step - self.skew_steps),
                           "time": time.time()}
            cluster.patch("v1", "Pod", namespace, victim, {
                "metadata": {"annotations": {
                    HEARTBEAT_ANNOTATION: _json.dumps(payload)}}})
        log.info("chaos: host fault %s/%s on %s (trip %d/%d)",
                 self.mode, victim, self.node, self.fired, self.trips)
        return victim


@dataclass
class CapacityLoss:
    """A host VANISHING from the cluster mid-run: the Node OBJECT is
    deleted (hypervisor death, node-pool scale-down, zone reclaim) —
    not merely flapped NotReady. The inventory then has no node
    claiming that host's cells, so they carve out as down and any
    binding covering them invalidates: the failure class elastic
    shrink-to-survive exists for (scheduler/core.py — a gang with no
    same-size rectangle left re-binds DEGRADED instead of starving).
    ``restore()`` re-creates the node (capacity returns: spare stock,
    pool scale-up), which is what grow-to-fill recovers into."""

    node: str
    fired: bool = False
    _saved: Optional[dict] = field(default=None, repr=False)

    def fire(self, cluster) -> bool:
        """Delete the node object; remembers it for restore()."""
        import copy
        node = cluster.get_or_none("v1", "Node", "", self.node)
        if node is None:
            return False
        self._saved = copy.deepcopy(node)
        cluster.delete("v1", "Node", "", self.node)
        self.fired = True
        log.info("chaos: capacity loss — node %s vanished", self.node)
        return True

    def restore(self, cluster) -> bool:
        """Bring the host back (fresh object identity, same name/labels
        — a replacement machine, not a resurrection)."""
        import copy
        if self._saved is None:
            return False
        obj = copy.deepcopy(self._saved)
        for stale in ("uid", "resourceVersion", "creationTimestamp"):
            obj.get("metadata", {}).pop(stale, None)
        cluster.create(obj)
        self._saved = None
        log.info("chaos: capacity restored — node %s is back", self.node)
        return True


# ---------------------------------------------------------------- the soak


# fault kinds the soak can inject between training segments
SOAK_FAULT_KINDS = ("pod-kill", "pod-fail", "api-burst", "watch-drop",
                    "truncate-ckpt", "hung-chief")


@dataclass
class SoakFault:
    """Inject `kind` once training has reached `at_step` global steps."""

    at_step: int
    kind: str

    def __post_init__(self):
        if self.kind not in SOAK_FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(choose from {SOAK_FAULT_KINDS})")


@dataclass
class ChaosSoak:
    """Drive one TPUJob through a scripted fault sequence, end to end.

    The control plane is real (FakeCluster + scheduler + the TPUJob
    reconciler, over a ChaosKubeClient); the data plane is real too — each
    time the gang is fully Running, a REAL training segment
    (runtime/worker.train, tiny transformer on the CPU mesh) runs
    in-process using the env the operator rendered into the chief pod
    (KFTPU_CHECKPOINT_DIR / KFTPU_RESUME_FROM), up to the next scripted
    fault's step. Faults then hit the cluster, the controller recovers
    (gang restart + resume), and the loop continues until the job reaches
    ``total_steps`` and the chief succeeds.

    Determinism: state.rng is checkpointed and the synthetic batch pool is
    seed-derived, so replayed steps recompute identical params — the
    report's final params must match an uninjected run bit-for-bit up to
    float tolerance (bench asserts ≤1e-5).
    """

    workdir: str
    faults: list = field(default_factory=list)
    total_steps: int = 6
    checkpoint_every: int = 2
    seed: int = 0
    global_batch: int = 8
    stall_timeout_s: int = 30
    restart_backoff_s: float = 0.02
    restart_backoff_max_s: float = 0.2
    wall_budget_s: float = 300.0
    namespace: str = "kubeflow"
    job_name: str = "chaos-soak"

    def _manifest(self, ckpt_dir: str) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": self.job_name,
                         "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {
                    "backoffLimit": len(self.faults) + 3,
                    "restartBackoffSeconds": self.restart_backoff_s,
                    "restartBackoffMaxSeconds": self.restart_backoff_max_s,
                    "stallTimeoutSeconds": self.stall_timeout_s,
                },
            },
        }

    def _chief_env(self, cluster, chief: str) -> dict:
        pod = cluster.get("v1", "Pod", self.namespace, chief)
        return {e["name"]: e.get("value", "")
                for e in pod["spec"]["containers"][0].get("env", [])}

    def _run_segment(self, env_map: dict, target: int):
        from ..obs.trace import adopt_trace_env
        from ..runtime.worker import train  # lazy: pulls in jax
        # adopt the operator-rendered trace contract for the segment:
        # the in-process "worker" reads the SAME env a real pod would,
        # so its window/ckpt spans stitch onto the job's trace id and
        # the goodput ledger can account the whole soak (ISSUE 10)
        with adopt_trace_env(env_map):
            return train(
                workload="transformer", steps=target,
                global_batch=self.global_batch, sync_every=1,
                checkpoint_dir=env_map.get("KFTPU_CHECKPOINT_DIR"),
                checkpoint_every=self.checkpoint_every,
                resume_from=env_map.get("KFTPU_RESUME_FROM"),
                seed=self.seed, handle_sigterm=False, workload_kwargs={})

    def _heartbeat(self, cluster, chief: str, step: int,
                   stale_by_s: float = 0.0) -> None:
        import json as _json
        from ..api.trainingjob import HEARTBEAT_ANNOTATION
        payload = _json.dumps({"step": step,
                               "time": time.time() - stale_by_s})
        cluster.patch("v1", "Pod", self.namespace, chief,
                      {"metadata": {"annotations":
                                    {HEARTBEAT_ANNOTATION: payload}}})

    def _inject(self, fault: SoakFault, cluster, chaos: ChaosKubeClient,
                ckpt_dir: str, chief: str, step: int) -> None:
        log.info("chaos soak: injecting %s at step %d", fault.kind, step)
        worker_pods = sorted(
            k8s.name_of(p)
            for p in cluster.list("v1", "Pod", self.namespace))
        victim = worker_pods[-1] if worker_pods else chief
        if fault.kind == "pod-kill":
            # preemption deletes the pod OBJECT (no Failed phase): the
            # vanish detector must gang-restart
            cluster.delete("v1", "Pod", self.namespace, victim)
        elif fault.kind == "pod-fail":
            cluster.fail_pod(self.namespace, victim, "chaos: worker died")
        elif fault.kind == "api-burst":
            # a 5xx burst right as the gang fails: reconcile attempts hit
            # injected errors and must retry through them
            chaos.fail_next(3)
            cluster.fail_pod(self.namespace, victim, "chaos: worker died")
        elif fault.kind == "watch-drop":
            chaos.drop_watch_streams()
            cluster.fail_pod(self.namespace, victim, "chaos: worker died")
        elif fault.kind == "truncate-ckpt":
            step_dir = latest_step_dir(ckpt_dir)
            if step_dir:
                truncate_checkpoint_payload(step_dir)
            cluster.fail_pod(self.namespace, victim, "chaos: worker died")
        elif fault.kind == "hung-chief":
            # live pod, stale heartbeat: only the stall watchdog recovers
            self._heartbeat(cluster, chief, step,
                            stale_by_s=self.stall_timeout_s + 5)

    def run(self) -> dict:
        from ..controllers.runtime import Manager
        from ..controllers.tpujob import (RESTART_COUNT_ANNOTATION,
                                          TrainingJobReconciler)
        from .fake import FakeCluster

        ckpt_dir = os.path.join(self.workdir, "ckpt")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        chaos = ChaosKubeClient(cluster)
        mgr = Manager(chaos)
        ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
        # watch-drop recovery depends on the periodic resync; keep it tight
        # so the soak converges quickly
        ctrl.resync_interval = 0.02
        cluster.create(self._manifest(ckpt_dir))

        pending = sorted((SoakFault(f.at_step, f.kind) if
                          not isinstance(f, SoakFault) else f
                          for f in self.faults), key=lambda f: f.at_step)
        report: dict = {"injected": [], "restart_reasons": [],
                        "segments": 0, "executed_steps": 0,
                        "outcome": "timeout"}
        deadline = time.monotonic() + self.wall_budget_s
        chief = f"{self.job_name}-worker-0-0"
        reached = 0
        while time.monotonic() < deadline:
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            job = cluster.get_or_none("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                      self.namespace, self.job_name)
            if job is None:
                report["outcome"] = "deleted"
                break
            cond = k8s.get_condition(job, "Restarting")
            if cond is not None and cond.get("status") == "True" and \
                    cond.get("reason") not in report["restart_reasons"]:
                report["restart_reasons"].append(cond.get("reason"))
            if k8s.condition_true(job, "Succeeded"):
                report["outcome"] = "succeeded"
                break
            if k8s.condition_true(job, "Failed"):
                report["outcome"] = "failed"
                report["failed_reason"] = k8s.get_condition(
                    job, "Failed").get("reason")
                break
            pods = cluster.list("v1", "Pod", self.namespace)
            running = [p for p in pods
                       if p.get("status", {}).get("phase") == "Running"]
            if len(running) != 2 or k8s.condition_true(job, "Restarting"):
                # gang down or mid-restart: let timers (restart backoff,
                # resync) fire and reconcile again
                time.sleep(0.03)
                continue
            target = min(pending[0].at_step, self.total_steps) if pending \
                else self.total_steps
            result = self._run_segment(self._chief_env(cluster, chief),
                                       target)
            report["segments"] += 1
            # steps this segment actually EXECUTED (its windows): the
            # soak's ground truth for restart-recompute — executed
            # minus final progress = steps replayed after restores,
            # which the goodput ledger must reproduce from spans alone
            report["executed_steps"] += int(result.steps)
            reached = max(reached, target)
            self._heartbeat(cluster, chief, reached)
            if pending and pending[0].at_step <= reached:
                fault = pending.pop(0)
                report["injected"].append({"step": reached,
                                           "kind": fault.kind})
                self._inject(fault, cluster, chaos, ckpt_dir, chief,
                             reached)
                continue
            if reached >= self.total_steps:
                # training done: the chief exits 0 and the operator
                # completes the job off the Succeeded phase
                cluster.set_pod_phase(self.namespace, chief, "Succeeded")
        job = cluster.get_or_none("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                  self.namespace, self.job_name)
        if job is not None:
            report["gang_restarts"] = int(k8s.annotations_of(job).get(
                RESTART_COUNT_ANNOTATION, "0"))
            from ..obs.trace import TRACE_ID_ANNOTATION
            report["trace_id"] = k8s.annotations_of(job).get(
                TRACE_ID_ANNOTATION, "")
        report["final_step"] = reached
        report["checkpoint_dir"] = ckpt_dir
        report["api_calls"] = chaos.calls
        report["api_faults"] = len(chaos.injected)
        for c in mgr.controllers:
            c.stop()
        return report


def final_params(checkpoint_dir: str):
    """Restore the params tree at the newest INTACT step (the integrity
    path — corrupted steps fall back). jax/orbax import is lazy."""
    from ..runtime.checkpoint import CheckpointManager
    mgr = CheckpointManager(checkpoint_dir)
    try:
        return mgr.restore_params()
    finally:
        mgr.close()


# ------------------------------------------------- numeric-fault injectors
# The sentinel tier's fault menu (runtime/sentinel.py NumericFaultHook):
# each injector renders the KFTPU_CHAOS_NUMERIC env contract the worker's
# hook consumes — the poison happens INSIDE the training loop (after the
# named step completes, so the damage surfaces in the NEXT window's
# metrics), not between segments like SoakFault. jax-free here; the hook
# imports jax lazily in-process.


@dataclass
class NaNInjector:
    """Multiply the params by NaN once step ``at_step`` completes — the
    hard-failure SDC: every downstream loss/grad is NaN, the sentinel's
    non-finite detector must trip within checkEverySteps."""

    at_step: int
    fires: int = 1
    node: Optional[str] = None
    kind = "nan"

    def spec(self) -> str:
        return f"nan:{self.at_step}"


@dataclass
class LossSpikePoisoner:
    """Scale the params by ``scale`` once ``at_step`` completes — a
    finite-but-wrong excursion only the rolling z-score detector sees
    (everything stays representable; nothing is NaN)."""

    at_step: int
    scale: float = 8.0
    fires: int = 1
    node: Optional[str] = None
    kind = "spike"

    def spec(self) -> str:
        return f"spike:{self.at_step}:{self.scale}"


@dataclass
class BitFlipGrad:
    """A silent bit-flip pinned to one host: a small multiplicative
    perturbation (exponent-bit flavor) fired ``fires`` times at the same
    step — the repeat-offender shape replay bisection exists for. The
    ``node`` pin names the host whose pod carries the evidence, so two
    trips fold two numeric-anomaly events onto it and its health score
    crosses the quarantine threshold."""

    at_step: int
    node: Optional[str] = None
    scale: float = 1.25
    fires: int = 2
    kind = "bitflip"

    def spec(self) -> str:
        return f"bitflip:{self.at_step}:{self.scale}"


@dataclass
class SentinelSoak:
    """Drive one TPUJob through a numeric-corruption episode, end to end:
    in-step detection → deliberate anomaly exit → operator LKG rollback
    (resumeFrom pinned to the last-known-good step, NOT the newest
    checkpoint) → clean re-run to completion; with a repeat-firing fault
    (BitFlipGrad), the second trip over the same LKG arms replay
    bisection and the third, clean segment publishes the verdict span.

    Same architecture as ChaosSoak (real control plane on FakeCluster,
    real in-process training segments using the env the operator rendered
    into the chief pod), with two twists: the fault fires INSIDE the
    worker via the KFTPU_CHAOS_NUMERIC hook (a fire-count marker file
    keeps it from re-firing forever across rollback segments), and on a
    trip the soak plays the pod's part — it annotates the victim pod with
    the evidence the real worker would have self-annotated and fails it,
    which is exactly what the operator's anomaly path watches for.

    ``corrupt_lkg=True`` additionally truncates the LKG step's payload at
    trip time: the rollback restore must then walk back to the
    next-oldest INTACT step (verify-then-fallback) and still converge.
    """

    workdir: str
    fault: Optional[object] = None     # one numeric injector (None = clean)
    total_steps: int = 10
    checkpoint_every: int = 2
    check_every: int = 1
    window_steps: int = 4
    spike_z: float = 4.0
    max_rollbacks: int = 3
    corrupt_lkg: bool = False
    seed: int = 0
    global_batch: int = 8
    wall_budget_s: float = 300.0
    namespace: str = "kubeflow"
    job_name: str = "sentinel-soak"

    def _manifest(self, ckpt_dir: str, span_path: str) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": self.job_name,
                         "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "observability": {"spanPath": span_path},
                "integrity": {"enabled": True,
                              "spikeZ": self.spike_z,
                              "windowSteps": self.window_steps,
                              "checkEverySteps": self.check_every},
                "replicaSpecs": {"TPU": {
                    "tpuTopology": "v5e-8",
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {
                    "backoffLimit": 3,
                    "maxAnomalyRollbacks": self.max_rollbacks,
                    "restartBackoffSeconds": 0.02,
                    "restartBackoffMaxSeconds": 0.2,
                },
            },
        }

    def _chief_env(self, cluster, chief: str) -> dict:
        pod = cluster.get("v1", "Pod", self.namespace, chief)
        return {e["name"]: e.get("value", "")
                for e in pod["spec"]["containers"][0].get("env", [])}

    # env the worker reads from os.environ (not train() kwargs): the
    # sentinel knobs the operator rendered into the pod, the rollback
    # directive, and the in-loop fault hook
    _PASS_ENV = ("KFTPU_INTEGRITY", "KFTPU_INTEGRITY_SPIKE_Z",
                 "KFTPU_INTEGRITY_WINDOW", "KFTPU_INTEGRITY_CHECK_EVERY",
                 "KFTPU_RESUME_STEP", "KFTPU_REPLAY_RANGE")

    def _run_segment(self, env_map: dict, target: int, mark_path: str):
        from ..obs.trace import adopt_trace_env
        from ..runtime import sentinel as sent
        from ..runtime.worker import train  # lazy: pulls in jax
        patched = {k: env_map.get(k) for k in self._PASS_ENV}
        if self.fault is not None:
            patched[sent.NUMERIC_FAULT_ENV] = self.fault.spec()
            patched[sent.NUMERIC_FAULT_MARK_ENV] = mark_path
            patched[sent.NUMERIC_FAULT_FIRES_ENV] = str(self.fault.fires)
        saved = {k: os.environ.get(k) for k in patched}
        for k, v in patched.items():
            if v:
                os.environ[k] = v
            else:
                os.environ.pop(k, None)
        try:
            with adopt_trace_env(env_map):
                return train(
                    workload="transformer", steps=target,
                    global_batch=self.global_batch, sync_every=1,
                    checkpoint_dir=env_map.get("KFTPU_CHECKPOINT_DIR"),
                    checkpoint_every=self.checkpoint_every,
                    resume_from=env_map.get("KFTPU_RESUME_FROM"),
                    seed=self.seed, handle_sigterm=False,
                    workload_kwargs={})
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def _heartbeat(self, cluster, chief: str, step: int) -> None:
        import json as _json
        from ..api.trainingjob import HEARTBEAT_ANNOTATION
        payload = _json.dumps({"step": step, "time": time.time()})
        cluster.patch("v1", "Pod", self.namespace, chief,
                      {"metadata": {"annotations":
                                    {HEARTBEAT_ANNOTATION: payload}}})

    def _victim(self, cluster, chief: str) -> str:
        """The pod that carries the evidence: the one on the fault's
        pinned node when there is a pin, else the chief."""
        node = getattr(self.fault, "node", None)
        if node:
            for p in cluster.list("v1", "Pod", self.namespace):
                if p.get("spec", {}).get("nodeName") == node:
                    return k8s.name_of(p)
        return chief

    def run(self) -> dict:
        import json as _json

        from ..controllers.runtime import Manager
        from ..controllers.tpujob import (RESTART_COUNT_ANNOTATION,
                                          TrainingJobReconciler)
        from ..api.trainingjob import (ANOMALY_ANNOTATION,
                                       ANOMALY_COUNT_ANNOTATION)
        from ..scheduler import health
        from .fake import FakeCluster

        ckpt_dir = os.path.join(self.workdir, "ckpt")
        span_path = os.path.join(self.workdir, "spans.jsonl")
        mark_path = os.path.join(self.workdir, "numeric-fault.mark")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes("v5e-8")
        chaos = ChaosKubeClient(cluster)
        mgr = Manager(chaos)
        ctrl = mgr.add(TrainingJobReconciler("TPUJob"))
        ctrl.resync_interval = 0.02
        cluster.create(self._manifest(ckpt_dir, span_path))

        report: dict = {"anomalies": [], "restart_reasons": [],
                        "segments": 0, "executed_steps": 0,
                        "outcome": "timeout", "lkg_corrupted": False}
        deadline = time.monotonic() + self.wall_budget_s
        chief = f"{self.job_name}-worker-0-0"
        reached = 0
        while time.monotonic() < deadline:
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            job = cluster.get_or_none("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                      self.namespace, self.job_name)
            if job is None:
                report["outcome"] = "deleted"
                break
            cond = k8s.get_condition(job, "Restarting")
            if cond is not None and cond.get("status") == "True" and \
                    cond.get("reason") not in report["restart_reasons"]:
                report["restart_reasons"].append(cond.get("reason"))
            if k8s.condition_true(job, "Succeeded"):
                report["outcome"] = "succeeded"
                break
            if k8s.condition_true(job, "Failed"):
                report["outcome"] = "failed"
                report["failed_reason"] = k8s.get_condition(
                    job, "Failed").get("reason")
                break
            pods = cluster.list("v1", "Pod", self.namespace)
            running = [p for p in pods
                       if p.get("status", {}).get("phase") == "Running"]
            if len(running) != 2 or k8s.condition_true(job, "Restarting"):
                time.sleep(0.03)
                continue
            env_map = self._chief_env(cluster, chief)
            result = self._run_segment(env_map, self.total_steps,
                                       mark_path)
            report["segments"] += 1
            report["executed_steps"] += int(result.steps)
            if result.anomaly:
                # play the failed pod's part: the in-process worker
                # can't self-annotate (no apiserver env), so the soak
                # attaches the evidence and fails the victim — the
                # operator's anomaly path takes it from here
                report["anomalies"].append(dict(result.anomaly))
                if self.corrupt_lkg and not report["lkg_corrupted"]:
                    lkg = result.anomaly.get("lkg")
                    step_dir = (os.path.join(ckpt_dir, str(int(lkg)))
                                if lkg else None)
                    if step_dir and os.path.isdir(step_dir):
                        truncate_checkpoint_payload(step_dir)
                        report["lkg_corrupted"] = True
                victim = self._victim(cluster, chief)
                cluster.patch(
                    "v1", "Pod", self.namespace, victim,
                    {"metadata": {"annotations": {
                        ANOMALY_ANNOTATION:
                            _json.dumps(result.anomaly)}}})
                cluster.fail_pod(self.namespace, victim,
                                 f"sentinel: {result.anomaly['kind']}")
                continue
            reached = self.total_steps
            self._heartbeat(cluster, chief, self.total_steps)
            if reached >= self.total_steps:
                cluster.set_pod_phase(self.namespace, chief, "Succeeded")
        job = cluster.get_or_none("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                  self.namespace, self.job_name)
        if job is not None:
            anns = k8s.annotations_of(job)
            report["gang_restarts"] = int(anns.get(
                RESTART_COUNT_ANNOTATION, "0"))
            report["rollbacks"] = int(anns.get(
                ANOMALY_COUNT_ANNOTATION, "0"))
            from ..obs.trace import TRACE_ID_ANNOTATION
            report["trace_id"] = anns.get(TRACE_ID_ANNOTATION, "")
        # bisection verdict: the worker's clean replay over the armed
        # range publishes an anomaly-bisection span — the evidence that
        # converts "this job is cursed" into a per-host verdict
        report["bisection"] = None
        try:
            with open(span_path, encoding="utf-8") as f:
                for line in f:
                    try:
                        span = _json.loads(line)
                    except ValueError:
                        continue
                    if span.get("name") == "anomaly-bisection":
                        report["bisection"] = span.get("attrs", span)
        except OSError:
            pass
        # hosts whose folded numeric-anomaly evidence crossed the
        # quarantine threshold (the scheduler's health sweep in
        # scheduler/core.py writes the actual quarantine annotation;
        # the score IS the criterion)
        cfg = health.HealthConfig()
        report["quarantined"] = sorted(
            k8s.name_of(n) for n in cluster.list("v1", "Node", "")
            if health.is_quarantined(n)
            or health.decayed_score(n) >= cfg.quarantine_threshold)
        report["final_step"] = reached
        report["checkpoint_dir"] = ckpt_dir
        report["span_path"] = span_path
        report["api_calls"] = chaos.calls
        report["api_faults"] = len(chaos.injected)
        for c in mgr.controllers:
            c.stop()
        return report


# ------------------------------------------------- serving-plane faults
# The serving resilience tier's fault menu (ISSUE 12): the failure
# classes one replica of a fleet WILL have, injectable against a real
# in-process ModelServer. jax-free like the rest of this module — the
# ChaosServable is a duck-typed servable (no device, host sleeps), so
# the whole ServingSoak runs without a chip.

SERVING_FAULT_KINDS = ("replica-crash", "wedge", "5xx-burst",
                       "cold-slow-start")


class ChaosServable:
    """Duck-typed servable with scriptable serving faults:

    - ``wedge()`` — accepts work, never answers (the hung-but-not-dead
      replica; only a client-side attempt timeout sees it) until
      ``unwedge()``;
    - ``fail_next(n, status)`` — the next n predicts raise with an
      ``http_status`` the server maps through (5xx burst);
    - ``slow_start(n, extra_s)`` — the next n predicts pay extra
      latency (a freshly-restarted cold replica warming its buckets);
    - ``tail_p``/``tail_s`` — seeded heavy-tail latency;
    - ``pause_every_s``/``pause_s``/``pause_phase_s`` — periodic
      whole-replica stalls (the GC-pause / compaction class the tail-
      at-scale hedging literature targets): a predict landing in a
      pause window waits it out, and its co-queued cohort piles up
      behind it. Replicas get offset phases, so a hedge to a DIFFERENT
      replica always finds one that is not pausing — the hedging A/B's
      workload.

    predict() echoes its instances after ``predict_s`` of host sleep —
    no numpy, no jax; the HTTP layer serializes whatever comes back.
    """

    def __init__(self, name: str = "chaos", predict_s: float = 0.004,
                 seed: int = 0, tail_p: float = 0.0,
                 tail_s: float = 0.0, pause_every_s: float = 0.0,
                 pause_s: float = 0.0, pause_phase_s: float = 0.0):
        self.name = name
        self.version = 1
        self.start_kind = "warm"
        self.predict_s = predict_s
        self.tail_p, self.tail_s = tail_p, tail_s
        self.pause_every_s = pause_every_s
        self.pause_s = pause_s
        self.pause_phase_s = pause_phase_s
        self._rng = random.Random(seed)
        self._proceed = threading.Event()
        self._proceed.set()
        self._lock = threading.Lock()
        self._fail_budget = 0
        self._fail_status = 500
        self._slow_left = 0
        self._slow_extra_s = 0.0
        self.predictions = 0

    # -------------------------------------------------------- fault knobs

    def wedge(self) -> None:
        """Accepts-never-responds: predicts block until unwedge()."""
        self._proceed.clear()

    def unwedge(self) -> None:
        self._proceed.set()

    @property
    def wedged(self) -> bool:
        return not self._proceed.is_set()

    def fail_next(self, n: int, status: int = 500) -> None:
        with self._lock:
            self._fail_budget += int(n)
            self._fail_status = int(status)

    def slow_start(self, n: int, extra_s: float) -> None:
        with self._lock:
            self._slow_left = int(n)
            self._slow_extra_s = float(extra_s)

    # ---------------------------------------------------- servable surface

    def predict(self, instances):
        self._proceed.wait()
        extra = 0.0
        with self._lock:
            if self._fail_budget > 0:
                self._fail_budget -= 1
                err = RuntimeError(
                    f"chaos: injected {self._fail_status}")
                err.http_status = self._fail_status
                raise err
            if self._slow_left > 0:
                self._slow_left -= 1
                extra += self._slow_extra_s
            if self.tail_p and self._rng.random() < self.tail_p:
                extra += self.tail_s
            self.predictions += 1
        if self.pause_every_s > 0:
            # a predict landing inside this replica's pause window
            # waits the pause out (and the queue behind it piles up)
            pos = (time.monotonic() + self.pause_phase_s) \
                % self.pause_every_s
            if pos < self.pause_s:
                extra += self.pause_s - pos
        time.sleep(self.predict_s + extra)
        return instances

    def metadata(self) -> dict:
        return {"model_spec": {"name": self.name},
                "stats": {"request_count": self.predictions,
                          "predict_seconds": 0.0}}

    def status(self) -> dict:
        return {"model_version_status": [
            {"version": self.version, "state": "AVAILABLE"}]}


class ServingReplicaHarness:
    """One in-process fleet member: a real ModelServer over a
    ChaosServable, restartable (the replacement-pod analog: same name,
    fresh process state, new port). Lazy serving import keeps this
    module's import jax-free path intact."""

    def __init__(self, name: str, span_path: Optional[str] = None,
                 model: str = "chaos", predict_s: float = 0.004,
                 seed: int = 0, tail_p: float = 0.0, tail_s: float = 0.0,
                 pause_every_s: float = 0.0, pause_s: float = 0.0,
                 pause_phase_s: float = 0.0,
                 max_batch: int = 8, max_latency_ms: float = 0.5):
        self.name = name
        self.span_path = span_path
        self.model = model
        self._servable_kw = dict(name=model, predict_s=predict_s,
                                 seed=seed, tail_p=tail_p, tail_s=tail_s,
                                 pause_every_s=pause_every_s,
                                 pause_s=pause_s,
                                 pause_phase_s=pause_phase_s)
        self._server_kw = dict(max_batch=max_batch,
                               max_latency_ms=max_latency_ms)
        self.servable: Optional[ChaosServable] = None
        self.server = None
        self.url = ""

    def start(self) -> str:
        from ..serving.http_server import ModelServer
        self.servable = ChaosServable(**self._servable_kw)
        self.server = ModelServer(host="127.0.0.1", port=0,
                                  sample_every=0,
                                  span_path=self.span_path,
                                  **self._server_kw)
        self.server.repository.add(self.servable)
        port = self.server.start()
        self.url = f"http://127.0.0.1:{port}"
        return self.url

    def inject(self, kind: str, **kw) -> None:
        """The serving fault menu, by kind (SERVING_FAULT_KINDS)."""
        if kind == "replica-crash":
            self.kill()
        elif kind == "wedge":
            self.servable.wedge()
        elif kind == "5xx-burst":
            self.servable.fail_next(kw.get("n", 10),
                                    kw.get("status", 500))
        elif kind == "cold-slow-start":
            self.servable.slow_start(kw.get("n", 20),
                                     kw.get("extra_s", 0.03))
        else:
            raise ValueError(f"unknown serving fault {kind!r} "
                             f"(choose from {SERVING_FAULT_KINDS})")
        log.info("chaos: serving fault %s on %s", kind, self.name)

    def kill(self) -> None:
        """SIGKILL-class crash: listener + live connections die,
        in-flight clients see a reset, nothing drains."""
        if self.server is not None:
            self.server.kill()

    def drain(self, timeout_s: float = 5.0) -> dict:
        return self.server.drain(timeout_s=timeout_s)

    def restart(self, slow_start_n: int = 0,
                slow_start_extra_s: float = 0.0) -> str:
        """The replacement pod: fresh server, same identity. A nonzero
        ``slow_start_n`` makes it a cold replica (the fourth fault
        kind) — its first n predicts pay ``slow_start_extra_s``."""
        self.stop()
        url = self.start()
        if slow_start_n:
            self.servable.slow_start(slow_start_n, slow_start_extra_s)
        return url

    def stop(self) -> None:
        if self.server is not None:
            if self.servable is not None:
                self.servable.unwedge()  # free any stuck batcher thread
            try:
                self.server.stop()
            except Exception:  # noqa: BLE001 — a killed server may throw
                pass
            self.server = None


@dataclass
class ServingSoak:
    """The kill-one-of-N availability soak (ISSUE 12): a real
    in-process N-replica fleet (ModelServers over ChaosServables)
    behind a FleetRouter, driven by a closed-loop multi-threaded
    client while scripted serving faults land. Four scenarios:

    - **kill**: SIGKILL one replica mid-load (plus a 5xx burst on a
      survivor, plus the victim's cold-slow-start restart — breaker
      probation re-admits it); asserts client success and zero
      duplicate deliveries.
    - **drain**: gracefully drain one replica mid-load; zero in-flight
      requests lost.
    - **wedge**: one replica accepts-and-never-responds; its breaker
      must eject it and, after recovery, probationally re-admit it.
    - **hedge A/B**: heavy-tail latency, hedging off vs on — the p99.9
      cut is the bench's acceptance number, hedge_waste the honest
      price.

    Every router span lands in ``span_path``; ``audit()`` re-reads the
    sink and checks the fleet ledgers sum to wall-clock (≤2% residual)
    with retries/hedges attributed, and that no request id was ever
    answered twice. bench.py --mode serving-fleet drives this.
    """

    span_path: str = ""
    replicas: int = 3
    model: str = "chaos"
    predict_s: float = 0.004
    seconds: float = 3.0
    threads: int = 6
    seed: int = 0
    attempt_timeout_s: float = 0.5
    max_retries: int = 3
    hedge_requests: int = 240
    # the hedge A/B's heavy tail: per-replica periodic pauses (GC /
    # compaction class), phases offset so no two replicas pause at once
    pause_every_s: float = 1.2
    pause_s: float = 0.08
    hedge_delay_ms: float = 15.0

    # ------------------------------------------------------------ plumbing

    def _harnesses(self, prefix: str, **kw) -> list:
        out = []
        for i in range(self.replicas):
            h = ServingReplicaHarness(
                f"{prefix}{i}", span_path=self.span_path,
                model=self.model, predict_s=self.predict_s,
                seed=self.seed * 1000 + i, **kw)
            h.start()
            out.append(h)
        return out

    def _router(self, harnesses, hedge: bool = False):
        from ..serving.fleet import (BreakerConfig, FleetConfig,
                                     FleetRouter)
        cfg = FleetConfig(
            max_retries=self.max_retries, backoff_s=0.01,
            default_deadline_s=max(5.0, 6 * self.attempt_timeout_s),
            attempt_timeout_s=self.attempt_timeout_s,
            poll_interval_s=0.1, poll_timeout_s=1.0,
            hedge=hedge, hedge_delay_ms=self.hedge_delay_ms)
        bcfg = BreakerConfig(half_life_s=2.0, trip_threshold=2.0,
                             release_threshold=1.0, open_s=0.5,
                             open_max_s=5.0, probe_successes=2)
        router = FleetRouter(
            replicas={h.name: h.url for h in harnesses},
            config=cfg, breaker_config=bcfg,
            span_path=self.span_path,
            rng=random.Random(self.seed))
        router.poll_once()
        router.start_polling()
        return router

    def _load(self, router, prefix: str, seconds: float,
              faults: Optional[list] = None) -> dict:
        """Closed-loop load from ``threads`` workers for ``seconds``;
        ``faults`` is [(at_frac, fn)] fired once by the driver thread.
        Returns per-request outcomes keyed by request id."""
        import json as _json
        body = _json.dumps({"instances": [[1.0, 2.0, 3.0]]}).encode()
        results: dict = {}
        res_lock = threading.Lock()
        counter = iter(range(10 ** 9))
        count_lock = threading.Lock()
        stop_at = time.monotonic() + seconds

        def worker():
            while time.monotonic() < stop_at:
                with count_lock:
                    rid = f"{prefix}{next(counter):05d}"
                try:
                    router.request(self.model, body, request_id=rid)
                    ok, err = True, ""
                except Exception as e:  # noqa: BLE001 — the soak counts
                    ok, err = False, f"{type(e).__name__}: {e}"
                with res_lock:
                    results[rid] = (ok, err)

        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.threads)]
        t0 = time.monotonic()
        for w in workers:
            w.start()
        pending = sorted(faults or [], key=lambda f: f[0])
        while pending and time.monotonic() < stop_at:
            frac = (time.monotonic() - t0) / max(seconds, 1e-9)
            if frac >= pending[0][0]:
                _, fn = pending.pop(0)
                fn()
            else:
                time.sleep(0.01)
        for w in workers:
            w.join(timeout=seconds + 10)
        ok = sum(1 for o, _ in results.values() if o)
        errs = sorted({e for o, e in results.values() if not o})
        return {"requests": len(results), "ok": ok,
                "success_pct": round(100.0 * ok / len(results), 3)
                if results else 0.0,
                "errors": errs[:5]}

    # ----------------------------------------------------------- scenarios

    def run_kill(self) -> dict:
        """SIGKILL one of N mid-load; a survivor takes a 5xx burst; the
        victim restarts cold and earns probational re-admission."""
        harnesses = self._harnesses("kill-r")
        router = self._router(harnesses)
        victim, bursty = harnesses[0], harnesses[-1]

        def crash():
            victim.inject("replica-crash")

        def burst():
            bursty.inject("5xx-burst", n=8, status=500)

        def resurrect():
            url = victim.restart(slow_start_n=10,
                                 slow_start_extra_s=0.02)
            router.set_replica_url(victim.name, url)

        try:
            report = self._load(router, "kill-", self.seconds,
                                faults=[(0.25, crash), (0.45, burst),
                                        (0.6, resurrect)])
            # the resurrected victim must be earning its way back:
            # half-open probes → closed (probation served)
            deadline = time.monotonic() + 10.0
            state = ""
            while time.monotonic() < deadline:
                state = router.replica(victim.name).breaker.state()
                if state == "closed":
                    break
                try:
                    router.request(
                        self.model,
                        b'{"instances": [[1.0, 2.0, 3.0]]}')
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(0.05)
            report["victim_readmitted"] = state == "closed"
            report["victim_breaker"] = \
                router.replica(victim.name).breaker.to_dict()
            report["fleet"] = router.snapshot()
            return report
        finally:
            router.close()
            for h in harnesses:
                h.stop()

    def run_drain(self) -> dict:
        """Gracefully drain one replica mid-load: readiness flips, the
        router routes away, in-flight work finishes — zero loss."""
        harnesses = self._harnesses("drain-r")
        router = self._router(harnesses)
        drained = harnesses[1]
        drain_report: dict = {}

        def drain():
            drain_report.update(drained.drain(timeout_s=5.0))

        try:
            report = self._load(router, "drain-", self.seconds,
                                faults=[(0.4, drain)])
            router.poll_once()
            rep = router.replica(drained.name)
            report["drain"] = drain_report
            report["router_saw_draining"] = bool(rep and rep.draining)
            report["in_flight_lost"] = \
                int(drain_report.get("inFlightRemaining", -1))
            return report
        finally:
            router.close()
            for h in harnesses:
                h.stop()

    def run_wedge(self) -> dict:
        """One replica wedges (accepts, never responds): its breaker
        must eject it; after recovery it is probationally re-admitted."""
        harnesses = self._harnesses("wedge-r")
        router = self._router(harnesses)
        victim = harnesses[-1]
        events: dict = {}

        def wedge():
            victim.inject("wedge")

        def spot_ejection():
            events["ejected_during_load"] = \
                router.replica(victim.name).breaker.state() == "open"

        def recover():
            victim.servable.unwedge()

        try:
            report = self._load(
                router, "wedge-", max(self.seconds, 3.0),
                faults=[(0.15, wedge), (0.55, spot_ejection),
                        (0.6, recover)])
            report.update(events)
            # keep trickling until probation completes
            deadline = time.monotonic() + 10.0
            state = ""
            while time.monotonic() < deadline:
                state = router.replica(victim.name).breaker.state()
                if state == "closed":
                    break
                try:
                    router.request(
                        self.model,
                        b'{"instances": [[1.0, 2.0, 3.0]]}')
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(0.05)
            report["ejected"] = bool(events.get("ejected_during_load")
                                     or router.replica(
                                         victim.name).breaker.trips)
            report["readmitted"] = state == "closed"
            report["victim_breaker"] = \
                router.replica(victim.name).breaker.to_dict()
            return report
        finally:
            router.close()
            for h in harnesses:
                h.stop()

    def run_hedge_ab(self) -> dict:
        """Heavy-tail latency, hedging off vs on: the tail (p99.9) must
        come down, and the duplicated work must land as hedge_waste —
        the honest price, never silent. The tail comes from per-replica
        periodic pauses with offset phases (no two replicas pause
        together), so a hedge to a different replica always finds a
        live one — the exact failure shape tail hedging exists for."""
        import json as _json
        body = _json.dumps({"instances": [[1.0, 2.0, 3.0]]}).encode()
        arms = {}
        phase_step = self.pause_every_s / max(1, self.replicas)
        for arm, hedge in (("off", False), ("on", True)):
            harnesses = []
            for i in range(self.replicas):
                h = ServingReplicaHarness(
                    f"hedge{arm}-r{i}", span_path=self.span_path,
                    model=self.model, predict_s=self.predict_s,
                    seed=self.seed * 1000 + i,
                    pause_every_s=self.pause_every_s,
                    pause_s=self.pause_s,
                    pause_phase_s=i * phase_step)
                h.start()
                harnesses.append(h)
            router = self._router(harnesses, hedge=hedge)
            lats: list = []
            lat_lock = threading.Lock()
            counter = iter(range(10 ** 9))
            count_lock = threading.Lock()
            per_thread = max(1, self.hedge_requests // self.threads)

            def worker():
                for _ in range(per_thread):
                    with count_lock:
                        rid = f"hedge{arm}-{next(counter):05d}"
                    t0 = time.monotonic()
                    try:
                        router.request(self.model, body,
                                       request_id=rid)
                        with lat_lock:
                            lats.append(time.monotonic() - t0)
                    except Exception:  # noqa: BLE001
                        pass

            try:
                workers = [threading.Thread(target=worker,
                                             daemon=True)
                           for _ in range(self.threads)]
                for w in workers:
                    w.start()
                for w in workers:
                    w.join(timeout=120)
            finally:
                router.close()
                for h in harnesses:
                    h.stop()
            lats.sort()

            def pct(q):
                return lats[min(len(lats) - 1, int(len(lats) * q))] \
                    if lats else 0.0

            arms[arm] = {
                "requests": len(lats),
                "p50_ms": round(pct(0.50) * 1e3, 2),
                "p99_ms": round(pct(0.99) * 1e3, 2),
                "p999_ms": round(pct(0.999) * 1e3, 2),
            }
        off, on = arms["off"], arms["on"]
        return {
            "off": off, "on": on,
            "p999_cut_pct": round(
                100.0 * (off["p999_ms"] - on["p999_ms"]) /
                off["p999_ms"], 1) if off["p999_ms"] else 0.0,
            "hedging_cuts_p999": on["p999_ms"] < off["p999_ms"],
        }

    # -------------------------------------------------------------- audit

    def audit(self) -> dict:
        """Re-read the span sink: (1) every fleet ledger's wall
        partition holds (upstream + retry + other ≈ wall, ≤2%
        residual) with retries/hedges as NAMED badput; (2) zero
        duplicate side effects — per request id, at most ONE server
        replica completed it ok, audited on the kill- and drain-
        scenario ids where at-most-once matters (a crashed attempt
        must read error, its failover ok). Hedge ids duplicate
        server-side BY DESIGN (that is hedge_waste); wedge ids may
        late-complete into a closed connection — both excluded, and
        the exclusion stated here rather than hidden."""
        from ..obs import goodput as gp
        from ..obs.trace import load_spans
        spans = load_spans(self.span_path)
        fleet = [s for s in spans
                 if s.get("name") == gp.FLEET_REQUEST_SPAN]
        sum_ok = 0
        wall_s = other_s = hedge_waste_s = retry_s = 0.0
        worst_resid = 0.0
        for s in fleet:
            ledger = (s.get("attrs") or {}).get("ledger") or {}
            if gp.fleet_sum_ok(ledger):
                sum_ok += 1
            wall = float(ledger.get("wallSeconds", 0.0))
            bad = ledger.get("badputSeconds") or {}
            wall_s += wall
            other_s += float(bad.get(gp.BADPUT_OTHER, 0.0))
            hedge_waste_s += float(bad.get(gp.SERVING_HEDGE_WASTE, 0.0))
            retry_s += float(bad.get(gp.SERVING_RETRY, 0.0))
            if wall:
                total = float(ledger.get("upstreamSeconds", 0.0)) + \
                    float(bad.get(gp.SERVING_RETRY, 0.0)) + \
                    float(bad.get(gp.BADPUT_OTHER, 0.0))
                worst_resid = max(worst_resid,
                                  abs(total - wall) / wall)
        # server-side at-most-once for the kill/drain ids: a crashed
        # or drained-away attempt's server span must not read ok
        # alongside its failover's
        audited_prefixes = ("kill-", "drain-")
        served: dict = {}
        audited = 0
        for s in spans:
            if s.get("name") != gp.SERVING_REQUEST_SPAN:
                continue
            rid = str(s.get("trace_id", ""))
            if not rid.startswith(audited_prefixes):
                continue
            audited += 1
            if (s.get("attrs") or {}).get("outcome") == "ok":
                served[rid] = served.get(rid, 0) + 1
        dup_served = sum(1 for c in served.values() if c > 1)
        return {
            "fleet_requests": len(fleet),
            "ledger_sum_ok": bool(fleet) and sum_ok == len(fleet),
            "other_residual_pct": round(
                100.0 * other_s / wall_s, 3) if wall_s else 0.0,
            "worst_request_residual_pct": round(
                100.0 * worst_resid, 3),
            "retry_badput_s": round(retry_s, 4),
            "hedge_waste_s": round(hedge_waste_s, 4),
            "audited_server_completions": audited,
            "duplicate_side_effects": dup_served,
            "duplicate_audit_scope": list(audited_prefixes),
        }

    def run(self) -> dict:
        report = {"kill": self.run_kill(),
                  "drain": self.run_drain(),
                  "wedge": self.run_wedge(),
                  "hedge_ab": self.run_hedge_ab()}
        report["audit"] = self.audit()
        return report
