"""Kubernetes REST wire-format helpers shared by the HTTP client and the
apiserver.

One module knows the path grammar both sides speak, so they cannot drift:

- core group:   /api/v1/[namespaces/{ns}/]{plural}[/{name}[/status]]
- named groups: /apis/{group}/{version}/[namespaces/{ns}/]{plural}[...]
- label selectors: ?labelSelector=k%3Dv,k2%3Dv2 (equality terms only — the
  selector model the rest of the framework uses)
- watch streams: collection GET + ?watch=true → chunked JSON lines
  {"type": ADDED|MODIFIED|DELETED|BOOKMARK, "object": {...}}

Reference parity: this is the slice of the kube API client-go exercises via
RESTMapper + dynamic client (the reference drives it through ksonnet's
client lib, ksonnet.go:92-197, and controller-runtime,
notebook_controller.go:57-144).

BOOKMARK events carry only metadata.resourceVersion. The apiserver emits one
for every mutation a filtered watch does NOT match, so a client can tell how
far a stream has caught up — the determinism hook the sync barrier in
http_client.HttpKubeClient builds on (kube's allowWatchBookmarks analog).
"""

from __future__ import annotations

import urllib.parse
from typing import Optional

from ..api import k8s

# Kind → plural for everything the framework ships; anything else falls back
# to the heuristic below (held identically by client and server).
KIND_PLURALS = {
    "Endpoints": "endpoints",
    "Ingress": "ingresses",
    "NetworkPolicy": "networkpolicies",
    "PodSecurityPolicy": "podsecuritypolicies",
    "ResourceQuota": "resourcequotas",
}

BOOKMARK = "BOOKMARK"


def plural_of(kind: str) -> str:
    if kind in KIND_PLURALS:
        return KIND_PLURALS[kind]
    lower = kind.lower()
    if lower.endswith("s") or lower.endswith("x") or lower.endswith("ch"):
        return lower + "es"
    if lower.endswith("y") and lower[-2:-1] not in "aeiou":
        return lower[:-1] + "ies"
    return lower + "s"


def api_prefix(api_version: str) -> str:
    """/api/v1 for the core group, /apis/{group}/{version} otherwise."""
    if "/" in api_version:
        return f"/apis/{api_version}"
    return f"/api/{api_version}"


def collection_path(api_version: str, kind: str,
                    namespace: Optional[str] = None) -> str:
    prefix = api_prefix(api_version)
    plural = plural_of(kind)
    if namespace and kind not in k8s.CLUSTER_SCOPED_KINDS:
        return f"{prefix}/namespaces/{namespace}/{plural}"
    return f"{prefix}/{plural}"


def object_path(api_version: str, kind: str, namespace: Optional[str],
                name: str) -> str:
    return f"{collection_path(api_version, kind, namespace)}/{name}"


def encode_selector(selector: dict[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(selector.items()))


def parse_selector(value: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for term in value.split(","):
        term = term.strip()
        if not term:
            continue
        if "==" in term:
            k, v = term.split("==", 1)
        elif "=" in term:
            k, v = term.split("=", 1)
        else:
            raise ValueError(f"unsupported selector term {term!r} "
                             "(equality terms only)")
        out[k.strip()] = v.strip()
    return out


class ParsedPath:
    """A decoded request path: what resource the verb addresses."""

    def __init__(self, api_version: str, plural: str,
                 namespace: Optional[str], name: Optional[str],
                 subresource: Optional[str]):
        self.api_version = api_version
        self.plural = plural
        self.namespace = namespace
        self.name = name
        self.subresource = subresource

    def kind_from(self, plural_to_kind: dict[str, str]) -> Optional[str]:
        return plural_to_kind.get(self.plural)


def parse_path(path: str) -> Optional[ParsedPath]:
    """Decode an /api or /apis resource path (query string already split
    off). Returns None for non-resource paths (/healthz, /version, ...)."""
    parts = [urllib.parse.unquote(p) for p in path.strip("/").split("/") if p]
    if not parts:
        return None
    if parts[0] == "api":
        if len(parts) < 2:
            return None
        api_version = parts[1]
        rest = parts[2:]
    elif parts[0] == "apis":
        if len(parts) < 3:
            return None
        api_version = f"{parts[1]}/{parts[2]}"
        rest = parts[3:]
    else:
        return None
    if not rest:
        return None
    namespace: Optional[str] = None
    if rest[0] == "namespaces" and len(rest) >= 3 and \
            not (len(rest) == 3 and rest[2] in ("status", "finalize")):
        # /namespaces/{ns}/{plural}... — but /namespaces/{name} (the
        # Namespace object itself, len 2) and /namespaces/{name}/status
        # (its subresource) address the Namespace resource, not a scope
        namespace, rest = rest[1], rest[2:]
    plural = rest[0]
    name = rest[1] if len(rest) > 1 else None
    subresource = rest[2] if len(rest) > 2 else None
    return ParsedPath(api_version, plural, namespace, name, subresource)


def status_body(code: int, reason: str, message: str) -> dict:
    """A kube Status object (what the client maps back to typed errors)."""
    return {
        "apiVersion": "v1", "kind": "Status",
        "status": "Failure" if code >= 400 else "Success",
        "code": code, "reason": reason, "message": message,
    }
