"""Kubernetes API abstraction.

- ``client``: the narrow client interface every controller and the CLI apply
  path program against (create/get/list/update/patch/delete/watch).
- ``fake``: an in-memory apiserver + scheduler implementing that interface —
  the envtest analog (SURVEY.md §4 tier 2) used by every controller test and
  by `kfctl apply --dry-run`. Models uids, resourceVersions, watches,
  owner-reference cascade deletion, nodes with TPU capacity, and all-or-nothing
  gang binding of pod groups.
- ``apply``: manifest-set apply/delete with per-object retry (the
  ksonnet.go applyComponent analog).
- ``wire`` / ``apiserver`` / ``http_client``: the kube REST wire format —
  an HTTP apiserver over any KubeClient backend, and HttpKubeClient, the
  real-cluster client (kubeconfig, watch streams) every controller and the
  CLI can run over unchanged.
"""

from .client import (AlreadyExistsError, ConflictError, KubeClient,
                     NotFoundError, WatchEvent)
from .fake import FakeCluster

__all__ = ["KubeClient", "FakeCluster", "WatchEvent", "NotFoundError",
           "ConflictError", "AlreadyExistsError"]
