"""In-memory apiserver + scheduler: the envtest analog.

The reference tests controllers against controller-runtime envtest (a real
etcd+apiserver, SURVEY.md §4 tier 2). We go one step further and model the
scheduler too, because gang scheduling of TPU slices is the core semantic the
operator must get right (SURVEY.md §7 hard part (a)) and the reference could
only test it E2E on a real cluster.

Modeled behavior:
- CRUD with uid + monotonically increasing resourceVersion, conflict detection
  on update, namespaced + cluster-scoped objects.
- Watches (queue-based), delivered synchronously on mutation.
- Owner-reference cascade deletion (background GC semantics).
- Nodes with allocatable resources, incl. the TPU extended resource
  ``google.com/tpu`` and the node selectors real TPU node pools carry.
- A scheduler that binds Pending pods to nodes; pods labeled with a pod-group
  (``scheduling.kubeflow.org/pod-group``) bind **all-or-nothing**: no pod of
  the group binds until every pod of the group fits simultaneously (the
  kube-batch PodGroup semantic tf-operator opts into via
  --enable-gang-scheduling, tf-job-operator.libsonnet:107-109,298-307).
- Deterministic time: `tick()` advances scheduling + pod phase transitions;
  tests drive transitions explicitly (`set_pod_phase`, `fail_pod`).
"""

from __future__ import annotations

import contextlib
import copy
import re
import threading
from typing import Callable, Optional

from ..api import k8s
from ..obs import controlplane as ctrlobs
from .client import (ADDED, AlreadyExistsError, ConflictError, DELETED,
                     KubeClient, MODIFIED, NotFoundError, Watch, WatchEvent)

POD_GROUP_LABEL = "scheduling.kubeflow.org/pod-group"
TPU_RESOURCE = "google.com/tpu"

# scope table lives in the shared API layer; re-exported for compatibility
CLUSTER_SCOPED_KINDS = k8s.CLUSTER_SCOPED_KINDS


def _resources_of(pod: dict) -> dict[str, float]:
    """Sum container resource requests (limits as fallback, the TPU idiom)."""
    total: dict[str, float] = {}
    for c in pod.get("spec", {}).get("containers", []) or []:
        res = c.get("resources", {}) or {}
        req = res.get("requests") or res.get("limits") or {}
        for k, v in req.items():
            total[k] = total.get(k, 0.0) + k8s.parse_quantity(v)
    return total


class FakeCluster(KubeClient):
    def __init__(self, auto_schedule: bool = True, auto_run: bool = True):
        self._objects: dict[tuple, dict] = {}
        self._watches: list[Watch] = []
        self._uid_n = 0
        self._rv_n = 0
        self._lock = threading.RLock()
        # auto_schedule: run the scheduler inside tick(); auto_run: scheduled
        # pods transition to Running on the next tick (tests can disable both).
        self.auto_schedule = auto_schedule
        self.auto_run = auto_run
        # hook for tests: called with each pod when it starts Running
        self.on_pod_running: Optional[Callable[[dict], None]] = None
        # mutating admission hooks (obj -> obj), run on create before
        # persistence — the MutatingWebhookConfiguration analog
        # (controllers/admission.py PodDefaultsWebhook plugs in here)
        self.admission_hooks: list[Callable[[dict], dict]] = []
        # server-side request ledger (obs/controlplane.py): every
        # TOP-LEVEL request is accounted per (component, verb, kind);
        # internal reentry (patch reads before merging, cascade GC
        # deletes, set_pod_phase's read-modify-write) stays one request
        # — that depth guard is what lets client-side audits reconcile
        # EXACTLY against this ledger
        self.audit = ctrlobs.ServerAudit()
        self._audit_local = threading.local()

    @contextlib.contextmanager
    def _audited(self, verb: str, kind: str):
        """Account one apiserver request at the outermost entry only.
        Failures count too (the server processed the request); list
        extras (object count/bytes) are filled into the yielded dict by
        the caller on success."""
        tl = self._audit_local
        depth = getattr(tl, "depth", 0)
        tl.depth = depth + 1
        info: dict = {}
        try:
            yield info
        finally:
            tl.depth = depth
            if depth == 0:
                self.audit.record(verb, kind, **info)

    # ------------------------------------------------------------- snapshot

    def to_snapshot(self) -> dict:
        """Serializable cluster state (used by kfctl to persist the simulated
        cluster across CLI invocations). Read-only: does not advance counters."""
        with self._lock:
            return {"objects": [copy.deepcopy(o) for o in self._objects.values()],
                    "counters": {"uid": self._uid_n, "rv": self._rv_n}}

    @classmethod
    def from_snapshot(cls, snap: dict, **kwargs) -> "FakeCluster":
        c = cls(**kwargs)
        for obj in snap.get("objects", []):
            key = c._key(obj)
            c._objects[key] = copy.deepcopy(obj)
        counters = snap.get("counters", {})
        # Counter restoration is CORRECTNESS, not bookkeeping: a restored
        # control plane that re-mints uid-1 collides trace ids (they are
        # uid-derived) and a rewound rv counter re-issues resourceVersions
        # watchers have already seen — orderings and conflict detection
        # both break. A legacy snapshot without counters derives them from
        # the objects' own high-water marks (an under-estimate only for
        # DELETED objects' rvs, which the stored counter covers whenever
        # it exists).
        uid, rv = counters.get("uid"), counters.get("rv")
        if uid is None or rv is None:
            max_uid = max_rv = 0
            for obj in c._objects.values():
                meta = obj.get("metadata", {})
                m = re.search(r"(\d+)$", str(meta.get("uid", "")))
                if m:
                    max_uid = max(max_uid, int(m.group(1)))
                try:
                    max_rv = max(max_rv,
                                 int(meta.get("resourceVersion", 0)))
                except (TypeError, ValueError):
                    pass
            uid = max_uid if uid is None else uid
            rv = max_rv if rv is None else rv
        c._uid_n = int(uid)
        c._rv_n = int(rv)
        return c

    def _next_uid(self) -> str:
        self._uid_n += 1
        return f"uid-{self._uid_n}"

    def _next_rv(self) -> str:
        self._rv_n += 1
        return str(self._rv_n)

    # ------------------------------------------------------------------ CRUD

    def _key(self, obj: dict) -> tuple:
        av, kind = k8s.gvk(obj)
        ns = "" if kind in CLUSTER_SCOPED_KINDS else k8s.namespace_of(obj, "default")
        return av, kind, ns, k8s.name_of(obj)

    def create(self, obj: dict) -> dict:
        with self._audited(ctrlobs.VERB_CREATE, str(obj.get("kind", ""))), \
                self._lock:
            obj = copy.deepcopy(obj)
            for hook in self.admission_hooks:
                obj = hook(obj)
            key = self._key(obj)
            if not key[3]:
                raise ValueError(f"object has no name: {obj}")
            if key in self._objects:
                raise AlreadyExistsError(f"{key[1]} {key[2]}/{key[3]} already exists")
            meta = obj.setdefault("metadata", {})
            if key[1] not in CLUSTER_SCOPED_KINDS:
                meta.setdefault("namespace", "default")
            meta["uid"] = self._next_uid()
            meta["resourceVersion"] = self._next_rv()
            self._objects[key] = obj
            self._broadcast(WatchEvent(ADDED, copy.deepcopy(obj)))
            return copy.deepcopy(obj)

    def get(self, api_version: str, kind: str, namespace: str, name: str) -> dict:
        with self._audited(ctrlobs.VERB_GET, kind), self._lock:
            ns = "" if kind in CLUSTER_SCOPED_KINDS else (namespace or "default")
            obj = self._objects.get((api_version, kind, ns, name))
            if obj is None:
                raise NotFoundError(f"{kind} {ns}/{name} not found")
            return copy.deepcopy(obj)

    def list(self, api_version: str, kind: str, namespace: Optional[str] = None,
             selector: Optional[dict] = None) -> list[dict]:
        with self._audited(ctrlobs.VERB_LIST, kind) as info, self._lock:
            out = []
            for (av, k, ns, _), obj in self._objects.items():
                if av != api_version or k != kind:
                    continue
                if namespace and ns != namespace:
                    continue
                if selector and not k8s.matches_selector(obj, selector):
                    continue
                out.append(copy.deepcopy(obj))
            out.sort(key=lambda o: (k8s.namespace_of(o), k8s.name_of(o)))
            # estimated on the SORTED payload: the client estimates on
            # the same first object, so byte totals reconcile exactly
            info["objects"] = len(out)
            info["nbytes"] = ctrlobs.payload_bytes(out)
            return out

    def _store_update(self, obj: dict, *, check_rv: bool = True) -> dict:
        key = self._key(obj)
        existing = self._objects.get(key)
        if existing is None:
            raise NotFoundError(f"{key[1]} {key[2]}/{key[3]} not found")
        if check_rv:
            rv = obj.get("metadata", {}).get("resourceVersion")
            if rv is not None and rv != existing["metadata"]["resourceVersion"]:
                raise ConflictError(
                    f"{key[1]} {key[3]}: resourceVersion conflict ({rv} != "
                    f"{existing['metadata']['resourceVersion']})"
                )
        obj = copy.deepcopy(obj)
        obj.setdefault("metadata", {})["uid"] = existing["metadata"]["uid"]
        obj["metadata"]["resourceVersion"] = self._next_rv()
        self._objects[key] = obj
        self._broadcast(WatchEvent(MODIFIED, copy.deepcopy(obj)))
        return copy.deepcopy(obj)

    def update(self, obj: dict) -> dict:
        with self._audited(ctrlobs.VERB_UPDATE, str(obj.get("kind", ""))), \
                self._lock:
            return self._store_update(obj)

    def update_status(self, obj: dict) -> dict:
        """Status-subresource update: merges only .status onto the stored spec."""
        with self._audited(ctrlobs.VERB_UPDATE_STATUS,
                           str(obj.get("kind", ""))), self._lock:
            key = self._key(obj)
            existing = self._objects.get(key)
            if existing is None:
                raise NotFoundError(f"{key[1]} {key[2]}/{key[3]} not found")
            merged = copy.deepcopy(existing)
            merged["status"] = copy.deepcopy(obj.get("status", {}))
            return self._store_update(merged, check_rv=False)

    def patch(self, api_version: str, kind: str, namespace: str, name: str,
              patch: dict) -> dict:
        with self._audited(ctrlobs.VERB_PATCH, kind), self._lock:
            existing = self.get(api_version, kind, namespace, name)
            merged = k8s.deep_merge(existing, patch)
            merged["metadata"]["resourceVersion"] = \
                existing["metadata"]["resourceVersion"]
            return self._store_update(merged)

    def delete(self, api_version: str, kind: str, namespace: str, name: str,
               cascade: bool = True) -> None:
        with self._audited(ctrlobs.VERB_DELETE, kind), self._lock:
            ns = "" if kind in CLUSTER_SCOPED_KINDS else (namespace or "default")
            key = (api_version, kind, ns, name)
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFoundError(f"{kind} {ns}/{name} not found")
            # the DELETED event carries a fresh rv (kube semantics) so
            # watch streams can measure catch-up past deletions
            obj["metadata"]["resourceVersion"] = self._next_rv()
            self._broadcast(WatchEvent(DELETED, copy.deepcopy(obj)))
            if cascade:
                self._gc(obj)

    def _gc(self, owner: dict) -> None:
        children = [o for o in self._objects.values() if k8s.is_owned_by(o, owner)]
        for child in children:
            av, kind, ns, name = self._key(child)
            try:
                self.delete(av, kind, ns, name, cascade=True)
            except NotFoundError:
                pass

    # ----------------------------------------------------------------- watch

    def watch(self, api_version: Optional[str] = None,
              kind: Optional[str] = None) -> Watch:
        with self._audited(ctrlobs.VERB_WATCH, kind or ctrlobs.KIND_ANY), \
                self._lock:
            w = Watch(api_version, kind)
            self._watches.append(w)
            return w

    def _broadcast(self, event: WatchEvent) -> None:
        self._watches = [w for w in self._watches if not w.closed]
        delivered = 0
        for w in self._watches:
            if w.matches(event.obj):
                delivered += 1
            w.deliver(event)
        # fan-out = delivered copies per broadcast event; counted even
        # at zero watchers (the broadcast happened, nobody listened)
        self.audit.record_broadcast(str(event.obj.get("kind", "")),
                                    delivered)

    # ------------------------------------------------------------- node pool

    def add_node(self, name: str, allocatable: dict[str, float],
                 labels: Optional[dict] = None) -> dict:
        node = k8s.make("v1", "Node", name, labels=labels or {})
        node["status"] = {"allocatable": dict(allocatable),
                          "conditions": [{"type": "Ready", "status": "True"}]}
        return self.create(node)

    def add_tpu_slice_nodes(self, topology_name: str, pool: str = "tpu-pool") -> list[dict]:
        """Provision the node pool for one slice: one node per TPU host,
        labeled the way GKE labels TPU node pools."""
        from ..api.topology import parse_topology
        topo = parse_topology(topology_name)
        nodes = []
        for h in range(topo.num_hosts):
            nodes.append(self.add_node(
                f"{pool}-{topology_name}-{h}",
                {TPU_RESOURCE: topo.chips_per_host, "cpu": 96, "memory": 2 ** 37},
                labels={
                    "cloud.google.com/gke-tpu-accelerator": f"tpu-{topo.generation.name}",
                    "cloud.google.com/gke-tpu-topology": topology_name,
                    "kubeflow.org/pool": pool,
                },
            ))
        return nodes

    # ------------------------------------------------------------- scheduler

    def _node_free(self) -> dict[str, dict[str, float]]:
        free = {}
        for (_, kind, _, name), node in list(self._objects.items()):
            if kind != "Node":
                continue
            free[name] = {
                r: k8s.parse_quantity(v)
                for r, v in (node.get("status", {})
                             .get("allocatable", {}) or {}).items()}
        for (_, kind, _, _), pod in list(self._objects.items()):
            if kind != "Pod":
                continue
            node_name = pod.get("spec", {}).get("nodeName")
            phase = pod.get("status", {}).get("phase")
            if node_name in free and phase in (None, "Pending", "Running"):
                for r, v in _resources_of(pod).items():
                    free[node_name][r] = free[node_name].get(r, 0.0) - v
        return free

    def _fits(self, pod: dict, free: dict[str, float], node: dict) -> bool:
        # cordoned nodes take no new pods (kubectl cordon /
        # spec.unschedulable — the quarantine path relies on this to
        # keep sub-slice gang pods off a bad host within a pool)
        if node.get("spec", {}).get("unschedulable"):
            return False
        sel = pod.get("spec", {}).get("nodeSelector") or {}
        if not all(k8s.labels_of(node).get(a) == b for a, b in sel.items()):
            return False
        return all(free.get(r, 0.0) >= v for r, v in _resources_of(pod).items())

    def _try_place(self, pods: list[dict], free: dict[str, dict[str, float]]
                   ) -> Optional[dict[str, str]]:
        """First-fit placement of a pod set onto the free map; returns
        pod-name → node-name or None if the whole set does not fit."""
        placement: dict[str, str] = {}
        free = {n: dict(f) for n, f in free.items()}
        nodes = {key[3]: obj for key, obj in self._objects.items()
                 if key[1] == "Node"}
        for pod in pods:
            placed = False
            for node_name in sorted(free):
                if self._fits(pod, free[node_name], nodes[node_name]):
                    placement[k8s.name_of(pod)] = node_name
                    for r, v in _resources_of(pod).items():
                        free[node_name][r] -= v
                    placed = True
                    break
            if not placed:
                return None
        return placement

    def schedule(self) -> int:
        """One scheduler pass. Gang groups bind all-or-nothing. Returns the
        number of pods bound."""
        with self._lock:
            pending = [o for o in self._objects.values()
                       if o.get("kind") == "Pod"
                       and not o.get("spec", {}).get("nodeName")
                       and o.get("status", {}).get("phase", "Pending") == "Pending"]
            if not pending:
                return 0
            bound = 0
            free = self._node_free()
            groups: dict[str, list[dict]] = {}
            singles: list[dict] = []
            for pod in pending:
                g = k8s.labels_of(pod).get(POD_GROUP_LABEL)
                (groups.setdefault(g, []) if g else singles).append(pod)

            def bind(pod: dict, node_name: str) -> None:
                nonlocal bound
                stored = self._objects[self._key(pod)]
                stored.setdefault("spec", {})["nodeName"] = node_name
                stored.setdefault("status", {}).setdefault("phase", "Pending")
                stored["metadata"]["resourceVersion"] = self._next_rv()
                self._broadcast(WatchEvent(MODIFIED, copy.deepcopy(stored)))
                for r, v in _resources_of(pod).items():
                    free[node_name][r] = free[node_name].get(r, 0.0) - v
                bound += 1

            for g, pods in groups.items():
                # all-or-nothing: the group's min-member annotation (set by the
                # operator) must be present before any member binds
                min_member = max(
                    int(k8s.annotations_of(p).get(
                        "scheduling.kubeflow.org/min-member", len(pods)))
                    for p in pods)
                if len(pods) < min_member:
                    continue
                placement = self._try_place(pods, free)
                if placement is None:
                    continue
                for pod in pods:
                    bind(pod, placement[k8s.name_of(pod)])
            for pod in singles:
                placement = self._try_place([pod], free)
                if placement:
                    bind(pod, placement[k8s.name_of(pod)])
            return bound

    # ------------------------------------------------------- pod lifecycle

    def set_pod_phase(self, namespace: str, name: str, phase: str,
                      message: str = "") -> dict:
        pod = self.get("v1", "Pod", namespace, name)
        pod.setdefault("status", {})["phase"] = phase
        if message:
            pod["status"]["message"] = message
        updated = self.update(pod)
        if phase == "Running" and self.on_pod_running:
            self.on_pod_running(copy.deepcopy(updated))
        return updated

    def fail_pod(self, namespace: str, name: str, message: str = "worker died") -> dict:
        return self.set_pod_phase(namespace, name, "Failed", message)

    def tick(self) -> None:
        """Advance one scheduling/run step: schedule pending pods, then start
        bound Pending pods (if auto_run)."""
        if self.auto_schedule:
            self.schedule()
        if self.auto_run:
            with self._lock:
                to_run = [
                    (k8s.namespace_of(o, "default"), k8s.name_of(o))
                    for o in self._objects.values()
                    if o.get("kind") == "Pod"
                    and o.get("spec", {}).get("nodeName")
                    and o.get("status", {}).get("phase", "Pending") == "Pending"
                ]
            for ns, name in to_run:
                self.set_pod_phase(ns, name, "Running")
