"""An HTTP apiserver speaking the Kubernetes REST wire format over any
KubeClient backend.

Two roles:

- **Mock apiserver for tests** (SURVEY.md §4 tier 2): the envtest analog at
  the wire level — HttpKubeClient and the whole controller matrix run
  against it exactly as they would against a real apiserver.
- **The simulated cluster as a service**: `kfctl serve-apiserver` exposes
  the persisted FakeCluster state over HTTP, so the manager process
  (`python -m kubeflow_tpu.controllers`) and the web apps can run as real,
  separate processes against a live endpoint.

Surface (the slice client-go uses, see cluster/wire.py):
GET/POST on collections, GET/PUT/PATCH/DELETE on objects, PUT on /status,
?labelSelector= on list and watch, and ?watch=true chunked JSON-line
streams with BOOKMARK events for filtered-out mutations (so clients can
measure stream catch-up; kube allowWatchBookmarks analog).
"""

from __future__ import annotations

import contextlib
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from ..api import k8s
from ..obs import controlplane as ctrlobs
from . import wire
from .client import (AlreadyExistsError, ConflictError, KubeClient,
                     NotFoundError)

log = logging.getLogger(__name__)

# kinds every server recognizes up front (anything else is learned from
# objects POSTed through this server)
_WELL_KNOWN_KINDS = list(k8s.CLUSTER_SCOPED_KINDS) + [
    "Pod", "Service", "StatefulSet", "Deployment", "ConfigMap", "Secret",
    "ServiceAccount", "Role", "RoleBinding", "PersistentVolumeClaim",
    "Event", "ResourceQuota", "Endpoints", "Ingress",
    "HorizontalPodAutoscaler", "TPUJob", "TFJob", "PyTorchJob", "MPIJob",
    "ChainerJob", "MXJob", "PaddleJob", "Notebook", "PodDefault",
    "Workflow", "ScheduledWorkflow", "StudyJob", "KubebenchJob",
    "Application", "VirtualService", "Gateway",
    # leader-election Leases (cluster/lease.py): HA controller replicas
    # coordinate through the same wire surface everything else uses
    "Lease",
]


class ApiError(Exception):
    def __init__(self, code: int, reason: str, message: str):
        super().__init__(message)
        self.code, self.reason, self.message = code, reason, message


def _typed_to_api_error(e: Exception) -> ApiError:
    if isinstance(e, NotFoundError):
        return ApiError(404, "NotFound", str(e))
    if isinstance(e, AlreadyExistsError):
        return ApiError(409, "AlreadyExists", str(e))
    if isinstance(e, ConflictError):
        return ApiError(409, "Conflict", str(e))
    return ApiError(500, "InternalError", f"{type(e).__name__}: {e}")


class ClusterAPIServer:
    """Serve a KubeClient backend over the kube REST wire format."""

    def __init__(self, backend: KubeClient, host: str = "127.0.0.1",
                 port: int = 8443):
        self.backend = backend
        self.host, self.port = host, port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._plural_to_kind: dict[str, str] = {}
        self._known_lock = threading.Lock()
        # resourceVersion high-water mark for BOOKMARKs / delete details:
        # FakeCluster exposes its counter directly; any other backend is
        # tracked from the rvs observed in responses and watch events
        self._rv_high = 0
        # wire-level request ledger (obs/controlplane.py): the SAME
        # vocabulary as FakeCluster's server-side audit, so sim and REST
        # report through one set of (component, verb, kind) rows. The
        # caller's X-Kftpu-Component header attributes the request (and
        # flows through to the backend's own ledger via the contextvar).
        self.audit = ctrlobs.ServerAudit()
        for kind in _WELL_KNOWN_KINDS:
            self.learn_kind(kind)

    # -- resourceVersion tracking -------------------------------------------

    def observe_rv(self, value) -> None:
        try:
            v = int(value)
        except (TypeError, ValueError):
            return
        with self._known_lock:
            if v > self._rv_high:
                self._rv_high = v

    def current_rv(self) -> int:
        n = getattr(self.backend, "_rv_n", None)
        if isinstance(n, int):
            return n
        with self._known_lock:
            return self._rv_high

    # -- kind bookkeeping ---------------------------------------------------

    def learn_kind(self, kind: str) -> None:
        if kind:
            with self._known_lock:
                self._plural_to_kind[wire.plural_of(kind)] = kind

    def kind_for(self, parsed: wire.ParsedPath) -> str:
        with self._known_lock:
            kind = self._plural_to_kind.get(parsed.plural)
        if kind:
            return kind
        raise ApiError(404, "NotFound",
                       f"the server could not find the requested resource "
                       f"(plural {parsed.plural!r})")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="kube-apiserver")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._stopping.set()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def _make_handler(server: ClusterAPIServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through logging
            log.debug("apiserver: " + fmt, *args)

        # -- plumbing -------------------------------------------------------

        def _send_json(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_error(self, e: ApiError) -> None:
            self._send_json(e.code,
                            wire.status_body(e.code, e.reason, e.message))

        def _read_body(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length)) if length else {}

        @staticmethod
        def _observed(result: dict) -> dict:
            server.observe_rv(result.get("metadata", {})
                              .get("resourceVersion"))
            return result

        # -- dispatch -------------------------------------------------------

        def _dispatch(self, verb: str) -> None:
            split = urlsplit(self.path)
            query = parse_qs(split.query)
            # drain the body up front on mutating verbs: replying before
            # reading it would desync HTTP/1.1 keep-alive connections
            body = None
            if verb in ("POST", "PUT", "PATCH"):
                try:
                    body = self._read_body()
                except (ValueError, json.JSONDecodeError) as e:
                    return self._send_error(
                        ApiError(400, "BadRequest", f"invalid body: {e}"))
            if split.path == "/healthz":
                return self._send_json(200, {"status": "ok"})
            if split.path == "/version":
                return self._send_json(
                    200, {"major": "1", "minor": "29",
                          "gitVersion": "v1.29.0-kubeflow-tpu-sim"})
            parsed = wire.parse_path(split.path)
            if parsed is None:
                return self._send_error(
                    ApiError(404, "NotFound", f"no route {split.path}"))
            # adopt the caller's component for attribution: this
            # handler thread's ledger rows (and the backend's, via the
            # contextvar) land under the caller's name, not unattributed
            comp = self.headers.get(ctrlobs.COMPONENT_HEADER)
            if verb == "GET" and query.get("watch", ["false"])[0] == "true":
                with ctrlobs.attributed(comp) if comp \
                        else contextlib.nullcontext():
                    return self._stream_watch(parsed, query)
            try:
                with ctrlobs.attributed(comp) if comp \
                        else contextlib.nullcontext():
                    self._send_json(200,
                                    self._handle(verb, parsed, query, body))
            except ApiError as e:
                self._send_error(e)
            except ValueError as e:  # bad selector/object → client error
                self._send_error(ApiError(400, "BadRequest", str(e)))
            except Exception as e:  # noqa: BLE001 — map to a Status object
                self._send_error(_typed_to_api_error(e))

        def _handle(self, verb: str, parsed: wire.ParsedPath,
                    query: dict, body) -> dict:
            backend = server.backend
            if verb == "GET":
                kind = server.kind_for(parsed)
                if parsed.name:
                    server.audit.record(ctrlobs.VERB_GET, kind)
                    return backend.get(parsed.api_version, kind,
                                       parsed.namespace or "", parsed.name)
                selector = None
                if query.get("labelSelector"):
                    selector = wire.parse_selector(query["labelSelector"][0])
                items = backend.list(parsed.api_version, kind,
                                     namespace=parsed.namespace,
                                     selector=selector)
                server.audit.record(ctrlobs.VERB_LIST, kind,
                                    objects=len(items),
                                    nbytes=ctrlobs.payload_bytes(items))
                return {"apiVersion": parsed.api_version,
                        "kind": f"{kind}List", "items": items}
            if verb == "POST":
                if parsed.name:
                    raise ApiError(405, "MethodNotAllowed",
                                   "POST targets collections")
                if parsed.namespace and \
                        body.get("kind") not in k8s.CLUSTER_SCOPED_KINDS:
                    body.setdefault("metadata", {}).setdefault(
                        "namespace", parsed.namespace)
                server.learn_kind(body.get("kind", ""))
                server.audit.record(ctrlobs.VERB_CREATE,
                                    str(body.get("kind", "")))
                return self._observed(backend.create(body))
            if verb == "PUT":
                if not parsed.name:
                    raise ApiError(405, "MethodNotAllowed",
                                   "PUT targets objects")
                if parsed.subresource == "status":
                    server.audit.record(ctrlobs.VERB_UPDATE_STATUS,
                                        str(body.get("kind", "")))
                    return self._observed(backend.update_status(body))
                if parsed.subresource:
                    raise ApiError(404, "NotFound",
                                   f"subresource {parsed.subresource!r}")
                server.audit.record(ctrlobs.VERB_UPDATE,
                                    str(body.get("kind", "")))
                return self._observed(backend.update(body))
            if verb == "PATCH":
                if not parsed.name:
                    raise ApiError(405, "MethodNotAllowed",
                                   "PATCH targets objects")
                kind = server.kind_for(parsed)
                server.audit.record(ctrlobs.VERB_PATCH, kind)
                return self._observed(backend.patch(
                    parsed.api_version, kind, parsed.namespace or "",
                    parsed.name, body))
            if verb == "DELETE":
                if not parsed.name:
                    raise ApiError(405, "MethodNotAllowed",
                                   "DELETE targets objects")
                kind = server.kind_for(parsed)
                cascade = query.get("propagationPolicy",
                                    ["Background"])[0] != "Orphan"
                server.audit.record(ctrlobs.VERB_DELETE, kind)
                backend.delete(parsed.api_version, kind,
                               parsed.namespace or "", parsed.name,
                               cascade=cascade)
                status = wire.status_body(200, "Deleted",
                                          f"{kind} {parsed.name} deleted")
                # rv high-water mark after the delete (incl. cascades), so
                # clients can barrier on their watch streams
                status["details"] = {"resourceVersion":
                                     str(server.current_rv())}
                return status
            raise ApiError(405, "MethodNotAllowed", verb)

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

        def do_PUT(self):
            self._dispatch("PUT")

        def do_PATCH(self):
            self._dispatch("PATCH")

        def do_DELETE(self):
            self._dispatch("DELETE")

        # -- watch streaming ------------------------------------------------

        def _write_chunk(self, data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        def _stream_watch(self, parsed: wire.ParsedPath,
                          query: dict) -> None:
            try:
                kind = server.kind_for(parsed)
                selector = None
                if query.get("labelSelector"):
                    selector = wire.parse_selector(
                        query["labelSelector"][0])
            except ApiError as e:
                return self._send_error(e)
            except ValueError as e:  # malformed selector → 400, not a crash
                return self._send_error(ApiError(400, "BadRequest", str(e)))

            # subscribe UNFILTERED so filtered-out mutations become
            # BOOKMARKs — the stream-catch-up signal (module docstring).
            # Subscribe BEFORE reading the current rv: a mutation in the gap
            # is then either queued on w or covered by the initial bookmark.
            w = server.backend.watch()
            server.audit.record(ctrlobs.VERB_WATCH, kind)
            current_rv = str(server.current_rv())
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                # initial bookmark: tells the client where the cluster is
                # NOW, so a barrier taken before this stream existed resolves
                self._write_chunk(json.dumps(
                    {"type": wire.BOOKMARK, "object": {
                        "apiVersion": parsed.api_version, "kind": kind,
                        "metadata": {"resourceVersion": current_rv}}}
                ).encode() + b"\n")
                import time as _time
                last_write = _time.monotonic()
                while not server._stopping.is_set():
                    ev = w.get(timeout=0.2)
                    if ev is None:
                        # heartbeat bookmark on idle streams: clients' read
                        # timeouts never fire and liveness is observable
                        if _time.monotonic() - last_write >= 5.0:
                            self._write_chunk(json.dumps(
                                {"type": wire.BOOKMARK, "object": {
                                    "apiVersion": parsed.api_version,
                                    "kind": kind,
                                    "metadata": {"resourceVersion": str(
                                        server.current_rv())}}}
                            ).encode() + b"\n")
                            last_write = _time.monotonic()
                        continue
                    last_write = _time.monotonic()
                    obj = ev.obj
                    server.observe_rv(obj.get("metadata", {})
                                      .get("resourceVersion"))
                    matches = (
                        obj.get("apiVersion") == parsed.api_version
                        and obj.get("kind") == kind
                        and (not parsed.namespace
                             or k8s.namespace_of(obj, "default")
                             == parsed.namespace)
                        and (selector is None
                             or k8s.matches_selector(obj, selector)))
                    if matches:
                        line = {"type": ev.type, "object": obj}
                        server.audit.record_delivered(kind)
                    else:
                        line = {"type": wire.BOOKMARK, "object": {
                            "apiVersion": parsed.api_version, "kind": kind,
                            "metadata": {"resourceVersion":
                                         obj.get("metadata", {})
                                         .get("resourceVersion", "")}}}
                    self._write_chunk(json.dumps(line).encode() + b"\n")
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass  # client went away or server socket closed
            finally:
                w.close()
                try:
                    self._write_chunk(b"")  # terminal chunk
                except OSError:
                    pass
                self.close_connection = True

    return Handler
