"""Manifest-set apply/delete against a KubeClient.

The analog of the reference's per-component apply with retry
(ksonnet.go:92-142 Apply, :148-197 applyComponent with 6x5s constant
backoff) and dependency ordering (namespaces/CRDs first).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Iterable, Optional

from ..api import k8s
from ..utils.retry import retry
from .client import KubeClient

log = logging.getLogger(__name__)


@dataclass
class ApplyResult:
    applied: list[tuple] = field(default_factory=list)
    failed: list[tuple] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.failed


def apply_manifests(
    client: KubeClient,
    objs: Iterable[dict],
    namespace: Optional[str] = None,
    attempts: int = 6,
    interval: float = 5.0,
    sleep=None,
) -> ApplyResult:
    """Apply in dependency order; per-object constant-backoff retry."""
    result = ApplyResult()
    for obj in k8s.sort_for_apply(objs):
        if (namespace and "namespace" not in obj.get("metadata", {})
                and obj.get("kind") not in k8s.CLUSTER_SCOPED_KINDS):
            k8s.set_namespace(obj, namespace)
        key = k8s.key_of(obj)
        try:
            kwargs = {"sleep": sleep} if sleep is not None else {}
            retry(lambda o=obj: client.apply(o), attempts=attempts,
                  interval=interval, desc=f"apply {key[1]}/{key[3]}", **kwargs)
            result.applied.append(key)
        except Exception as e:
            log.error("apply failed for %s: %s", key, e)
            result.failed.append((key, str(e)))
    return result


def delete_manifests(client: KubeClient, objs: Iterable[dict]) -> None:
    """Delete in reverse apply order (workloads before CRDs/namespaces)."""
    for obj in reversed(k8s.sort_for_apply(objs)):
        client.delete_many([obj])
