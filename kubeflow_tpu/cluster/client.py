"""The Kubernetes client interface.

Controllers, the CLI apply path, and the web apps all program against this
narrow surface; implementations are the in-memory FakeCluster (tests,
dry-run) and a REST client against a real apiserver (gated: no cluster in the
dev environment). This mirrors how the reference splits client-go usage from
reconciler logic (controller-runtime's client.Client).
"""

from __future__ import annotations

import copy
import queue
from dataclasses import dataclass
from typing import Callable, Iterable, Optional


class KubeError(Exception):
    pass


class NotFoundError(KubeError):
    pass


class AlreadyExistsError(KubeError):
    pass


class ConflictError(KubeError):
    """resourceVersion mismatch on update — caller must re-read and retry."""


ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: str   # ADDED | MODIFIED | DELETED
    obj: dict


class Watch:
    """A watch subscription: a queue of WatchEvents with an optional
    (apiVersion, kind) filter. close() detaches it from the server."""

    def __init__(self, api_version: Optional[str] = None, kind: Optional[str] = None):
        self.api_version = api_version
        self.kind = kind
        self.events: "queue.Queue[WatchEvent]" = queue.Queue()
        self.closed = False

    def matches(self, obj: dict) -> bool:
        if self.api_version and obj.get("apiVersion") != self.api_version:
            return False
        if self.kind and obj.get("kind") != self.kind:
            return False
        return True

    def deliver(self, event: WatchEvent) -> None:
        if not self.closed and self.matches(event.obj):
            self.events.put(event)

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            return self.events.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self.closed = True


class KubeClient:
    """Abstract client. All objects are manifest dicts (see api.k8s)."""

    def create(self, obj: dict) -> dict:
        raise NotImplementedError

    def get(self, api_version: str, kind: str, namespace: str, name: str) -> dict:
        raise NotImplementedError

    def list(self, api_version: str, kind: str, namespace: Optional[str] = None,
             selector: Optional[dict] = None) -> list[dict]:
        raise NotImplementedError

    def update(self, obj: dict) -> dict:
        raise NotImplementedError

    def update_status(self, obj: dict) -> dict:
        raise NotImplementedError

    def patch(self, api_version: str, kind: str, namespace: str, name: str,
              patch: dict) -> dict:
        raise NotImplementedError

    def delete(self, api_version: str, kind: str, namespace: str, name: str,
               cascade: bool = True) -> None:
        raise NotImplementedError

    def watch(self, api_version: Optional[str] = None,
              kind: Optional[str] = None) -> Watch:
        raise NotImplementedError

    # -- conveniences shared by all implementations -------------------------

    def get_or_none(self, api_version: str, kind: str, namespace: str,
                    name: str) -> Optional[dict]:
        try:
            return self.get(api_version, kind, namespace, name)
        except NotFoundError:
            return None

    def apply(self, obj: dict) -> dict:
        """Create-or-update (kubectl apply semantics, spec-level replace).

        No-op when nothing changes: reconcilers apply their children every
        pass while watching those same kinds, so an unconditional update
        (which bumps resourceVersion and broadcasts MODIFIED) would
        re-enqueue the owner forever.
        """
        from ..api import k8s
        existing = self.get_or_none(*k8s.key_of(obj))
        if existing is None:
            return self.create(obj)
        merged = dict(existing)
        for key in ("spec", "data", "stringData", "rules", "webhooks",
                    "subsets", "roleRef", "subjects"):
            if key in obj:
                merged[key] = obj[key]
        meta = dict(existing.get("metadata", {}))
        for key in ("labels", "annotations"):
            if obj.get("metadata", {}).get(key):
                meta[key] = obj["metadata"][key]
        merged["metadata"] = meta
        if k8s.snapshot(merged) == k8s.snapshot(existing):
            return existing
        return self.update(merged)

    def delete_many(self, objs: Iterable[dict]) -> None:
        from ..api import k8s
        for obj in objs:
            try:
                self.delete(*k8s.key_of(obj))
            except NotFoundError:
                pass


def apply_annotations(obj: dict, updates: dict) -> dict:
    """Fold an annotation-update map onto an object in place (the kube
    null-delete convention: a None value REMOVES the key). The shape
    every conflict-safe annotation writer's ``mutate`` uses, so patch
    semantics and update semantics cannot drift."""
    anns = obj.setdefault("metadata", {}).setdefault("annotations", {})
    for key, value in updates.items():
        if value is None:
            anns.pop(key, None)
        else:
            anns[key] = value
    return obj


def update_with_conflict_retry(
        client: KubeClient, api_version: str, kind: str, namespace: str,
        name: str, mutate: Callable[[dict], Optional[dict]],
        max_attempts: int = 5) -> dict:
    """Optimistic-concurrency read-modify-write: re-read → re-apply
    ``mutate`` → update with the read's resourceVersion as precondition;
    a ConflictError (another writer landed in between) re-reads and
    re-applies. THE write primitive for every annotation RMW in the
    control plane (restart counters, bindings, resize histories, health
    folds, final ledgers): a blind patch computes its value from a
    possibly-stale read and silently loses the other writer's update —
    this loses nothing, ever, at the price of a bounded retry.

    ``mutate(obj)`` receives a deep copy of the FRESH object and returns
    the object to write (mutating in place and returning it is fine), or
    None to skip the write entirely (the decision is re-made per
    attempt, so "already done" short-circuits are conflict-safe too).

    NotFoundError propagates — callers that tolerate a deleted object
    catch it, same as they would around a patch.
    """
    last: Optional[ConflictError] = None
    for attempt in range(max_attempts):
        obj = client.get(api_version, kind, namespace, name)
        desired = mutate(copy.deepcopy(obj))
        if desired is None:
            return obj
        desired.setdefault("metadata", {})["resourceVersion"] = \
            obj.get("metadata", {}).get("resourceVersion")
        try:
            return client.update(desired)
        except ConflictError as e:
            last = e
            # lazy import: obs is dependency-free but cluster/ must not
            # grow import-time edges it does not need
            from ..obs import registry as obsreg
            obsreg.counter(
                "kftpu_conflict_retries_total",
                "read-modify-write attempts retried after a "
                "resourceVersion conflict", labels=("kind",)).labels(
                    kind=kind).inc()
    raise last if last is not None else KubeError(
        f"update_with_conflict_retry: no attempt made for "
        f"{kind} {namespace}/{name}")
