"""Anonymous usage reporter (the spartakus analog), strictly opt-out.

The reference deploys spartakus-volunteer with a random cluster id and
prints an opt-out warning at init (kubeflow/common/spartakus.libsonnet:75;
coordinator.go:166-190 sets the usageId param and logs how to disable).
Same contract here: anonymized facts only (counts and versions, never
names), a persisted random usage id, and reporting disabled by either the
``KF_DISABLE_USAGE_REPORT`` env or ``enabled=False``.
"""

from __future__ import annotations

import json
import logging
import os
import random
import urllib.request
from typing import Callable, Optional

from ..api import k8s
from ..cluster.client import KubeClient

log = logging.getLogger(__name__)

DISABLE_ENV = "KF_DISABLE_USAGE_REPORT"

OPT_OUT_WARNING = (
    "Usage reporting is enabled: anonymized cluster facts (component "
    "counts, TPU topology, versions — never names or data) are reported "
    "to improve the project. Disable with %s=1 or "
    "spartakus.enabled=false in the KfDef." % DISABLE_ENV)


def collect_facts(client: KubeClient, usage_id: int) -> dict:
    """Anonymized cluster facts: shapes and counts, no identifiers."""
    nodes = client.list("v1", "Node")
    tpu_chips = 0
    topologies: dict[str, int] = {}
    for n in nodes:
        alloc = n.get("status", {}).get("allocatable", {}) or {}
        tpu_chips += int(k8s.parse_quantity(alloc.get("google.com/tpu", 0)))
        topo = k8s.labels_of(n).get("cloud.google.com/gke-tpu-topology")
        if topo:
            topologies[topo] = topologies.get(topo, 0) + 1
    return {
        "usageId": usage_id,
        "nodes": len(nodes),
        "tpuChips": tpu_chips,
        "tpuTopologies": topologies,
        "namespaces": len(client.list("v1", "Namespace")),
        "trainingJobs": sum(
            len(client.list(av, kind))
            for av, kind in (("tpu.kubeflow.org/v1alpha1", "TPUJob"),
                             ("kubeflow.org/v1beta2", "TFJob"),
                             ("kubeflow.org/v1beta2", "PyTorchJob"),
                             ("kubeflow.org/v1alpha1", "MPIJob"))),
        "notebooks": len(client.list("kubeflow.org/v1alpha1", "Notebook")),
    }


class UsageReporter:
    def __init__(self, client: KubeClient, *, enabled: bool = True,
                 usage_id: Optional[int] = None,
                 sink: Optional[Callable[[dict], None]] = None,
                 report_url: Optional[str] = None):
        env_disabled = os.environ.get(DISABLE_ENV, "") not in ("", "0",
                                                               "false")
        self.enabled = enabled and not env_disabled
        self.client = client
        # random id like the reference's usageId param (coordinator.go)
        self.usage_id = usage_id if usage_id is not None else \
            random.SystemRandom().randint(1, 2 ** 31 - 1)
        self.report_url = report_url
        self.sink = sink or self._http_sink
        if not self.enabled:
            log.info("usage reporting disabled")
        elif sink is None and not report_url:
            log.warning("usage reporting enabled but no report_url/sink "
                        "configured — reports will be dropped")
        else:
            log.warning(OPT_OUT_WARNING)

    def _http_sink(self, payload: dict) -> None:
        if not self.report_url:
            return
        req = urllib.request.Request(
            self.report_url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10).close()

    def report_once(self) -> Optional[dict]:
        """Collect + send one report; returns the payload (None when
        disabled). Reporting failures are logged, never raised."""
        if not self.enabled:
            return None
        try:
            payload = collect_facts(self.client, self.usage_id)
            self.sink(payload)
        except Exception as e:  # noqa: BLE001 - telemetry must not break
            log.warning("usage report failed: %s", e)
            return None
        return payload
