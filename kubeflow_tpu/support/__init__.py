"""Support services (SURVEY.md §2.7 small components).

- ``metric_collector``: availability prober exporting the
  ``kubeflow_availability`` Prometheus gauge (metric-collector/
  service-readiness/kubeflow-readiness.py:20-37).
- ``spartakus``: opt-out anonymous usage reporter
  (kubeflow/common/spartakus.libsonnet:75; opt-out warning
  coordinator.go:166-190).
- ``echo_server``: minimal HTTP echo app, the CI routing target
  (components/echo-server/main.py).
- ``https_redirect``: plain→TLS redirect shim
  (components/https-redirect/main.py).
"""
