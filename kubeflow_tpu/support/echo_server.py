"""Echo server: the minimal routing-verification target.

The reference's components/echo-server/main.py (deployed by
kubeflow/common/echo-server.libsonnet) exists so CI can verify
ingress/Ambassador routes end-to-end; the response echoes the request so
path-rewrite and header behavior is observable.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler

from ..webapps._http import ThreadedServer


class EchoServer(ThreadedServer):
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _echo(self, body: bytes = b""):
                payload = json.dumps({
                    "method": self.command,
                    "path": self.path,
                    "headers": dict(self.headers.items()),
                    "body": body.decode("utf-8", "replace"),
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._echo()

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                self._echo(self.rfile.read(length) if length else b"")

        super().__init__(Handler, host=host, port=port, name="echo-server")
