"""Echo server: the minimal routing-verification target.

The reference's components/echo-server/main.py (deployed by
kubeflow/common/echo-server.libsonnet) exists so CI can verify
ingress/Ambassador routes end-to-end; the response echoes the request so
path-rewrite and header behavior is observable.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class EchoServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _echo(self, body: bytes = b""):
                payload = json.dumps({
                    "method": self.command,
                    "path": self.path,
                    "headers": dict(self.headers.items()),
                    "body": body.decode("utf-8", "replace"),
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._echo()

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                self._echo(self.rfile.read(length) if length else b"")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="echo-server")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
