"""Deploy prober: periodic end-to-end deploy drills → Prometheus metrics.

The reference's click-to-deploy prober (testing/test_deploy_app.py:16-35)
runs the bootstrap deploy API end-to-end on a schedule and exports its
own Prometheus gauges/counters — CI doubling as availability monitoring.
This is that component as a first-class support service: each cycle
drives the bootstrap server's real surface (create → show-until-ready →
delete), records success/failure counters and the last cycle's latency,
and serves the standard text exposition through the shared
MetricsServer handler (``metrics_text`` duck type).

Deployable entrypoint (the deploy-prober manifest renders the same
target as BOOTSTRAP_URL)::

    python -m kubeflow_tpu.support.deploy_prober \
        --url http://kubeflow-bootstrapper.kubeflow-admin:8085 \
        --interval 600
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from ..obs.registry import Registry

SUCCESS_COUNT = "deploy_prober_success_total"
FAILURE_COUNT = "deploy_prober_failure_total"
LATENCY_GAUGE = "deploy_prober_last_cycle_seconds"
UP_GAUGE = "deploy_prober_last_cycle_ok"


class DeployProber:
    """One prober instance per bootstrap server URL.

    The cycle mirrors what the deploy UI does (webapps/static/deploy.js):
    POST /kfctl/e2eDeploy, poll GET /kfctl/apps/{name} until the
    Available condition lands, then POST /kfctl/apps/delete — so a green
    prober means the whole control-plane path a user clicks through is
    live, not just that a port answers."""

    # the poll window's shape when nothing is configured: wait up to
    # half the probe interval (a drill may not outlive its own cadence),
    # clamped so a tiny interval still polls a few times and a huge one
    # does not wait forever on a dead deploy
    MIN_POLL_WINDOW_S = 2.0
    MAX_POLL_WINDOW_S = 120.0

    def __init__(self, url: str, app_name: str = "prober",
                 components: Optional[list] = None,
                 timeout_s: float = 30.0,
                 poll_tries: Optional[int] = None,
                 poll_sleep_s: float = 0.2,
                 interval_s: Optional[float] = None,
                 clock=time.monotonic):
        """``poll_tries``/``poll_sleep_s`` bound the wait-for-Available
        loop. When poll_tries is unset it SCALES with ``interval_s``
        (the probe cadence): window = clamp(interval/2, 2s..120s),
        tries = window / sleep — so a prober pointed at a slow real
        bootstrap server (minutes-long deploys) no longer reports
        chronic false failures off the old hard-coded ~2s window
        (ADVICE.md round 5)."""
        self.url = url.rstrip("/")
        self.app_name = app_name
        self.components = components
        self.timeout_s = timeout_s
        self.poll_sleep_s = poll_sleep_s
        if poll_tries is None:
            window = min(self.MAX_POLL_WINDOW_S,
                         max(self.MIN_POLL_WINDOW_S,
                             (interval_s or 0.0) / 2.0))
            poll_tries = max(1, int(window / max(poll_sleep_s, 1e-6)))
        self.poll_tries = poll_tries
        self._clock = clock
        self._lock = threading.Lock()
        self.successes = 0
        self.failures = 0
        self.last_cycle_s = 0.0
        self.last_ok = 0
        self.last_error: Optional[str] = None
        # shared-registry exposition (obs/registry.py), own Registry per
        # instance, names unchanged from the hand-rolled text
        self.registry = Registry()
        self._g_ok = self.registry.gauge(
            UP_GAUGE, "1 if the last deploy drill succeeded")
        self._c_success = self.registry.counter(
            SUCCESS_COUNT, "deploy drills that succeeded")
        self._c_failure = self.registry.counter(
            FAILURE_COUNT, "deploy drills that failed")
        self._g_latency = self.registry.gauge(
            LATENCY_GAUGE, "wall seconds of the last deploy drill")

    # -- wire helpers --------------------------------------------------------

    def _post(self, path: str, payload: dict) -> dict:
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read())

    def _get(self, path: str) -> dict:
        with urllib.request.urlopen(self.url + path,
                                    timeout=self.timeout_s) as resp:
            return json.loads(resp.read())

    # -- the drill -----------------------------------------------------------

    def _cycle(self) -> None:
        payload = {"name": self.app_name, "platform": "existing"}
        if self.components:
            payload["components"] = self.components
        conds: list = []
        try:
            self._post("/kfctl/e2eDeploy", payload)
            for _ in range(self.poll_tries):
                show = self._get(f"/kfctl/apps/{self.app_name}")
                conds = show.get("conditions") or []
                if any(str(c).startswith("Available=True") for c in conds):
                    return
                time.sleep(self.poll_sleep_s)
            raise RuntimeError(
                f"app {self.app_name} never reported Available=True "
                f"(last conditions: {conds})")
        finally:
            # clean up even when the deploy phase itself fails — a
            # leaked app makes e2eDeploy take the idempotent skip-create
            # path forever after, so the drill would silently stop
            # exercising create/generate
            try:
                self._post("/kfctl/apps/delete", {"name": self.app_name})
            except Exception:  # noqa: BLE001 — best-effort: a failed
                pass           # delete must never mask the drill result

    def probe(self) -> bool:
        """One full deploy drill; never raises — a failed deploy IS the
        signal this prober exists to record."""
        t0 = self._clock()
        ok = False
        err: Optional[str] = None
        try:
            self._cycle()
            ok = True
        except Exception as e:  # noqa: BLE001 - outage is data
            err = f"{type(e).__name__}: {e}"
        dt = self._clock() - t0
        with self._lock:
            self.last_cycle_s = dt
            self.last_ok = 1 if ok else 0
            if ok:
                self.successes += 1
            else:
                self.failures += 1
                self.last_error = err
        self._g_ok.set(1 if ok else 0)
        self._g_latency.set(round(dt, 3))
        (self._c_success if ok else self._c_failure).inc()
        return ok

    def metrics_text(self) -> str:
        return self.registry.render()

    def run_forever(self, interval_s: float = 600.0,
                    stop: Optional[threading.Event] = None) -> None:
        from .metric_collector import run_probe_loop
        run_probe_loop(self.probe, interval_s, stop)


def main(argv: Optional[list] = None) -> int:
    from .metric_collector import prober_main

    def add_args(p):
        p.add_argument("--app-name", default="prober")
        p.add_argument("--poll-tries", type=int, default=None,
                       help="wait-for-Available polls per drill "
                            "(default: scaled from --interval)")
        p.add_argument("--poll-sleep", type=float, default=0.2,
                       help="seconds between readiness polls")

    return prober_main(
        argv, description=__doc__.splitlines()[0],
        url_env="BOOTSTRAP_URL", default_interval=600.0,
        make_prober=lambda args: DeployProber(
            args.url, app_name=args.app_name,
            poll_tries=args.poll_tries, poll_sleep_s=args.poll_sleep,
            interval_s=args.interval),
        add_args=add_args,
        banner="deploy prober")


if __name__ == "__main__":
    raise SystemExit(main())
