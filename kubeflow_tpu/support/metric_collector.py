"""Availability prober: periodic endpoint checks → Prometheus gauge.

The reference's metric-collector probes the IAP-protected kubeflow
endpoint with an OIDC token and exports ``kubeflow_availability``
(metric-collector/service-readiness/kubeflow-readiness.py:20-37, deployed
by kubeflow/gcp/prototypes/metric-collector.jsonnet). Here the prober is
auth-agnostic (optional header provider) and the exposition is the
standard Prometheus text format on /metrics.
"""

from __future__ import annotations

import threading
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Callable, Optional

from ..obs.registry import Registry
from ..webapps._http import ThreadedServer

GAUGE_NAME = "kubeflow_availability"
PROBE_COUNT = "kubeflow_availability_probe_total"
FAILURE_COUNT = "kubeflow_availability_probe_failures_total"


class AvailabilityProber:
    def __init__(self, url: str, timeout_s: float = 10.0,
                 header_provider: Optional[Callable[[], dict]] = None,
                 fetch: Optional[Callable[[str, dict, float], int]] = None):
        self.url = url
        self.timeout_s = timeout_s
        self.header_provider = header_provider or (lambda: {})
        self._fetch = fetch or self._http_fetch
        self._lock = threading.Lock()
        self.available = 0
        self.probes = 0
        self.failures = 0
        self.last_error: Optional[str] = None
        # exposition via the shared registry (obs/registry.py) — an OWN
        # Registry per prober instance (several coexist in one test
        # process); metric names unchanged from the hand-rolled text
        # this replaced, so existing scrape configs keep working
        self.registry = Registry()
        self._g_up = self.registry.gauge(
            GAUGE_NAME, "1 if the kubeflow endpoint is up")
        self._c_probes = self.registry.counter(
            PROBE_COUNT, "availability probes attempted")
        self._c_failures = self.registry.counter(
            FAILURE_COUNT, "availability probes that failed")

    @staticmethod
    def _http_fetch(url: str, headers: dict, timeout_s: float) -> int:
        req = urllib.request.Request(url, headers=headers)
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status

    def probe(self) -> bool:
        """One availability check; updates the gauge. The prober never
        raises — unreachable IS the signal it exists to record."""
        ok = False
        err: Optional[str] = None
        try:
            status = self._fetch(self.url, self.header_provider(),
                                 self.timeout_s)
            ok = 200 <= status < 400
            if not ok:
                err = f"status {status}"
        except Exception as e:  # noqa: BLE001 - outage is data, not a crash
            err = str(e)
        with self._lock:
            self.probes += 1
            self.available = 1 if ok else 0
            if not ok:
                self.failures += 1
                self.last_error = err
        self._c_probes.inc()
        self._g_up.set(1 if ok else 0)
        if not ok:
            self._c_failures.inc()
        return ok

    def metrics_text(self) -> str:
        return self.registry.render()

    def run_forever(self, interval_s: float = 30.0,
                    stop: Optional[threading.Event] = None) -> None:
        run_probe_loop(self.probe, interval_s, stop)


def run_probe_loop(probe: Callable[[], bool], interval_s: float,
                   stop: Optional[threading.Event] = None) -> None:
    """Shared probe loop for the support probers (availability, deploy):
    probe, wait, repeat until the stop event fires."""
    stop = stop or threading.Event()
    while not stop.is_set():
        probe()
        stop.wait(interval_s)


def prober_main(argv: Optional[list], *, description: str, url_env: str,
                default_interval: float, make_prober,
                add_args=None, banner: str) -> int:
    """Shared container entrypoint for the support probers: --url with an
    env fallback (the manifests render env only), a lazily-validated
    PROBE_INTERVAL_S, /metrics bound on all interfaces (Prometheus
    scrapes the pod IP). ``make_prober(args)`` builds the prober;
    ``add_args(parser)`` registers prober-specific flags."""
    import argparse
    import os
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--url", default=os.environ.get(url_env),
                   help=f"target base URL (env fallback: {url_env})")
    p.add_argument("--interval", type=float, default=None,
                   help="seconds between drills (env fallback: "
                        f"PROBE_INTERVAL_S; default {default_interval})")
    p.add_argument("--metrics-port", type=int, default=8000)
    p.add_argument("--metrics-host", default="0.0.0.0")
    if add_args:
        add_args(p)
    args = p.parse_args(argv)
    if not args.url:
        p.error(f"--url (or {url_env}) is required")
    if args.interval is None:
        raw = os.environ.get("PROBE_INTERVAL_S")
        try:
            args.interval = float(raw) if raw else default_interval
        except ValueError:
            p.error(f"PROBE_INTERVAL_S={raw!r} is not a number")
    prober = make_prober(args)
    server = MetricsServer(prober, host=args.metrics_host,
                           port=args.metrics_port)
    port = server.start()
    print(f"{banner} exporting on :{port}/metrics", flush=True)
    prober.run_forever(interval_s=args.interval)
    return 0


def main(argv: Optional[list] = None) -> int:
    """Container entrypoint for the metric-collector manifest
    (manifests/observability.py renders TARGET_URL/PROBE_INTERVAL_S)."""
    return prober_main(
        argv, description=__doc__.splitlines()[0], url_env="TARGET_URL",
        default_interval=30.0,
        make_prober=lambda args: AvailabilityProber(args.url),
        banner="metric collector")


class MetricsServer(ThreadedServer):
    """Serves the prober's /metrics (prometheus scrape target)."""

    def __init__(self, prober: AvailabilityProber, host: str = "127.0.0.1",
                 port: int = 0):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = prober.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        super().__init__(Handler, host=host, port=port,
                         name="metric-collector")


if __name__ == "__main__":
    raise SystemExit(main())
