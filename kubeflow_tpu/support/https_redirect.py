"""HTTP→HTTPS redirect shim (components/https-redirect/main.py analog)."""

from __future__ import annotations

from http.server import BaseHTTPRequestHandler
from typing import Optional

from ..webapps._http import ThreadedServer


def strip_port(host_header: str) -> str:
    """Host header without the port; IPv6 literals ([::1]:8080) keep
    their brackets intact."""
    if host_header.startswith("["):
        return host_header.split("]")[0] + "]"
    return host_header.rsplit(":", 1)[0] if ":" in host_header \
        else host_header


class RedirectServer(ThreadedServer):
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 target_host: Optional[str] = None):
        fixed_host = target_host

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                host = fixed_host or \
                    strip_port(self.headers.get("Host") or "localhost")
                self.send_response(301)
                self.send_header("Location", f"https://{host}{self.path}")
                self.send_header("Content-Length", "0")
                self.end_headers()

            do_POST = do_GET
            do_HEAD = do_GET

        super().__init__(Handler, host=host, port=port,
                         name="https-redirect")
