"""HTTP→HTTPS redirect shim (components/https-redirect/main.py analog)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class RedirectServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 target_host: Optional[str] = None):
        fixed_host = target_host

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                host = fixed_host or \
                    (self.headers.get("Host") or "localhost").split(":")[0]
                self.send_response(301)
                self.send_header("Location", f"https://{host}{self.path}")
                self.send_header("Content-Length", "0")
                self.end_headers()

            do_POST = do_GET
            do_HEAD = do_GET

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="https-redirect")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
