"""Logical-axis sharding rules: parameter names → mesh axes.

Models annotate parameters with *logical* axis names ("embed", "mlp",
"heads", "kv", "vocab", "expert", "stage", ...). A LogicalRules table maps
logical axes to mesh axes (or None = replicated). This decouples model code
from the parallelism strategy: the same model runs pure-DP, FSDP, TP, EP or
any combination by swapping rules — the GSPMD idiom (flax logical axes /
t5x partitioning).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AxisTarget = Union[str, tuple[str, ...], None]


class LogicalRules:
    """Ordered mapping logical-axis-name → mesh axis (or axes, or None).

    ``dcn_unsafe`` names logical axes whose sharding must be DROPPED on
    a multi-slice mesh (``dcn_aware``): a gather-indexed table dim (the
    tok_embed vocab axis) sharded over tensor forces the SPMD
    partitioner through a full rematerialization of the table — on one
    slice that reshard rides cheap ICI, across slices it pays the DCN
    link every step (the MULTICHIP_r05 "involuntary full
    rematerialization" pathology the comm analyzer flags as
    ``dcn_full_reshard``)."""

    def __init__(self, rules: Sequence[tuple[str, AxisTarget]],
                 dcn_unsafe: Sequence[str] = ()):
        self.rules = list(rules)
        self._map = dict(self.rules)
        self.dcn_unsafe = tuple(dcn_unsafe)

    def dcn_aware(self, num_slices: int) -> "LogicalRules":
        """The rules this table resolves to on a ``num_slices``-slice
        mesh: on a single slice, itself; across a DCN boundary, a copy
        with every ``dcn_unsafe`` logical axis replicated — no
        tensor/sequence-sharded leaf is forced through a DCN-crossing
        all-gather/permute (rung 1 of the multi-slice ISSUE; measured in
        PERF.md "Multi-slice DCN training")."""
        if num_slices <= 1 or not self.dcn_unsafe:
            return self
        unsafe = set(self.dcn_unsafe)
        return LogicalRules(
            [(name, None if name in unsafe else target)
             for name, target in self.rules],
            dcn_unsafe=self.dcn_unsafe)

    def spec_for(self, logical_axes: Sequence[Optional[str]],
                 mesh: Optional[Mesh] = None) -> P:
        """PartitionSpec for a param annotated with logical axes.

        Mesh axes of size 1 (or absent) are dropped to keep XLA specs clean;
        a mesh axis may be consumed by at most one dimension of a given param
        (first dimension wins, later dims replicate), matching GSPMD rules.
        """
        used: set[str] = set()
        out = []
        for ax in logical_axes:
            target = self._map.get(ax) if ax is not None else None
            if target is None:
                out.append(None)
                continue
            targets = (target,) if isinstance(target, str) else tuple(target)
            kept = []
            for t in targets:
                if mesh is not None and mesh.shape.get(t, 1) <= 1:
                    continue
                if t in used:
                    continue
                kept.append(t)
                used.add(t)
            out.append(tuple(kept) if len(kept) > 1 else (kept[0] if kept else None))
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def sharding_for(self, logical_axes: Sequence[Optional[str]],
                     mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.spec_for(logical_axes, mesh))

    def tree_shardings(self, mesh: Mesh, logical_tree) -> dict:
        """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
        return jax.tree.map(
            lambda axes: self.sharding_for(axes, mesh),
            logical_tree,
            is_leaf=lambda x: isinstance(x, tuple) and all(
                a is None or isinstance(a, str) for a in x),
        )


def weight_update_spec(spec: P, shape: Sequence[int], mesh: Mesh,
                       axes: Sequence[str]) -> Optional[P]:
    """Augment a param's PartitionSpec so ONE additional dimension is
    sharded over ``axes`` — the per-leaf rule of the cross-replica sharded
    weight update (Xu et al.): gradients reduce-scatter into this spec,
    optimizer state lives in it, new params all-gather out of it.

    The first (leading) dimension that is still unsharded in ``spec`` and
    divisible by the product of the usable axes wins. Axes already consumed
    by ``spec`` (e.g. fsdp on an FSDP-sharded param) are skipped — the
    update for such a leaf is already distributed. Returns None when no
    dimension qualifies (scalars, odd sizes): the caller keeps the leaf's
    existing sharding, a per-leaf fallback, not an error.
    """
    used: set[str] = set()
    for entry in spec:
        if entry is None:
            continue
        for t in (entry,) if isinstance(entry, str) else tuple(entry):
            used.add(t)
    free = tuple(a for a in axes
                 if a not in used and mesh.shape.get(a, 1) > 1)
    if not free:
        return None
    degree = 1
    for a in free:
        degree *= mesh.shape[a]
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, dim in enumerate(shape):
        if entries[i] is None and dim and dim % degree == 0:
            entries[i] = free if len(free) > 1 else free[0]
            return P(*entries)
    return None


# Default rule tables. "embed"-style activations shard over tensor; params
# additionally shard over fsdp for ZeRO-3-style weight sharding.
TRANSFORMER_RULES = LogicalRules([
    ("batch", ("data", "fsdp")),
    ("sequence", "sequence"),
    ("embed", "fsdp"),          # weight-sharding axis for FSDP
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("kv", None),
    ("head_dim", None),
    ("vocab", "tensor"),
    # gather-indexed table dim (tok_embed's vocab axis): sharded over
    # tensor like the matmul "vocab" above on a single slice, but the
    # embedding GATHER cannot run against a table sharded on its indexed
    # dim — the partitioner replicates-then-repartitions it, and on a
    # multi-slice mesh that transition crosses DCN every step, so
    # dcn_aware() replicates this axis there (dcn_unsafe below)
    ("vocab_table", "tensor"),
    ("expert", "expert"),
    ("stage", "pipeline"),
    ("layers", "pipeline"),     # stacked-block leading dim (pipeline stages)
], dcn_unsafe=("vocab_table",))

RESNET_RULES = LogicalRules([
    ("batch", ("data", "fsdp")),
    ("height", None),
    ("width", None),
    ("in_chan", None),
    ("out_chan", "tensor"),     # channel-wise TP for the widest convs
    ("features", "tensor"),
    ("classes", None),
])
