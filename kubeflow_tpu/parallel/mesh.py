"""Device-mesh construction from slice topology + sharding spec.

Axis order is DCN-major → ICI-minor so that:
- the ``data`` axis (pure DP) maps across slices (DCN all-reduce once per
  step, latency-tolerant gradient sums), and
- ``tensor``/``sequence`` (latency-sensitive per-layer collectives) map to
  the innermost ICI dimension.

This is the standard TPU sharding recipe ("How to Scale Your Model"): pick a
mesh, annotate shardings, let XLA insert the collectives.

Reference parity: the analog of the operator-rendered TF_CONFIG cluster dict
(SURVEY.md §3.2) consumed at workload startup — here the contract (env) is
consumed by `mesh_from_contract` in the worker bootstrap.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..api.topology import TopologyContract
from ..api.trainingjob import ShardingSpec, dcn_crossing_axes

# Canonical axis order (DCN-major). "data" first: multi-slice DP rides DCN.
MESH_AXES = ShardingSpec.AXES  # ("data", "fsdp", "expert", "pipeline", "sequence", "tensor")



def mesh_shape_from_sharding(sharding: ShardingSpec, num_devices: int) -> dict[str, int]:
    """Resolve the sharding spec against the global device count."""
    return sharding.resolve(num_devices)


def build_mesh(sharding: Optional[ShardingSpec] = None,
               devices: Optional[list] = None) -> Mesh:
    """Build the global mesh over all (or the given) devices.

    Device order: jax's default device list is already ICI-contiguous per
    process; reshaping row-major into the axis sizes puts the innermost axes
    (tensor/sequence) on ICI neighbors and the outermost (data) across
    slices/hosts — the DCN-major layout.
    """
    devices = list(devices if devices is not None else jax.devices())
    sharding = sharding or ShardingSpec()
    sizes = sharding.resolve(len(devices))
    shape = tuple(sizes[a] for a in MESH_AXES)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def mesh_from_contract(contract: TopologyContract,
                       sharding: Optional[ShardingSpec] = None) -> Mesh:
    """Worker-side mesh construction from the operator-rendered contract.

    Validates that the contract's chip count matches the visible devices
    (after jax.distributed.initialize every process sees the global device
    list).
    """
    expected = contract.slice_topology.num_chips * contract.num_slices
    devices = jax.devices()
    if len(devices) != expected:
        raise RuntimeError(
            f"topology contract promises {expected} chips "
            f"({contract.slice_topology.name} x {contract.num_slices}) but "
            f"jax sees {len(devices)} devices — slice not fully up?"
        )
    return build_mesh(sharding, devices)


def num_slices_of(mesh: Mesh) -> int:
    """Slices this mesh spans, from the devices' own ``slice_index``
    (real multi-slice TPU backends stamp it; virtual CPU devices do not
    — callers that emulate slices pass their count explicitly). Jax
    interns Mesh instances (two constructions over the same devices are
    the SAME object), so the count deliberately lives on the devices /
    the caller, never as mutable Mesh state."""
    indices = {getattr(d, "slice_index", None) for d in mesh.devices.flat}
    indices.discard(None)
    return max(1, len(indices))


def slice_crossing_axes(mesh: Mesh,
                        num_slices: Optional[int] = None) -> tuple:
    """Mesh axes whose transitions cross the DCN slice boundary (the
    jax-side wrapper over the jax-free ``api.trainingjob.
    dcn_crossing_axes`` — DCN-major row-major enumeration, slice id =
    flat position // chips_per_slice)."""
    n = num_slices if num_slices is not None else num_slices_of(mesh)
    return dcn_crossing_axes(
        {a: int(mesh.shape[a]) for a in mesh.axis_names}, n,
        axes=tuple(mesh.axis_names))


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes over which the batch is split (everything data-parallel-like)."""
    return tuple(a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1) or ("data",)


def replica_axes(mesh: Mesh) -> tuple[str, ...]:
    """Non-trivial data-parallel axes — the axes a sharded weight update
    (ZeRO-2) distributes optimizer state over. Unlike ``data_axes`` there
    is no size-1 fallback: an empty tuple means every chip already holds
    the whole model alone and there is nothing to shard the update over."""
    return tuple(a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1)


def replica_degree(mesh: Mesh) -> int:
    """Number of data-parallel replicas (product of the replica axes)."""
    n = 1
    for a in replica_axes(mesh):
        n *= mesh.shape[a]
    return n


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dim sharded over data+fsdp; sequence dim over the sequence axis."""
    return NamedSharding(mesh, P(data_axes(mesh)))


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    dp = 1
    for a in ("data", "fsdp"):
        dp *= mesh.shape.get(a, 1)
    if global_batch % dp:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"data-parallel degree {dp}")
    return global_batch // dp


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
