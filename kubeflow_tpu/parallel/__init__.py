"""Parallelism as data: mesh construction + sharding rules + collectives.

The reference has NO tensor/pipeline/sequence/expert parallelism anywhere
(SURVEY.md §2.5 row 5 — it only scales data-parallel replica counts and
delegates the rest to the launched frameworks). This package supplies those
natively, the TPU way: one jax.sharding.Mesh with named axes, GSPMD sharding
annotations, and XLA collectives over ICI/DCN — no NCCL, no MPI, no
user-space communication library.
"""

from .mesh import (MESH_AXES, build_mesh, data_axes, local_batch_size,
                   mesh_from_contract, mesh_shape_from_sharding)
from .sharding_rules import LogicalRules, RESNET_RULES, TRANSFORMER_RULES

__all__ = [
    "MESH_AXES", "build_mesh", "mesh_from_contract", "mesh_shape_from_sharding",
    "data_axes", "local_batch_size", "LogicalRules", "RESNET_RULES",
    "TRANSFORMER_RULES",
]
