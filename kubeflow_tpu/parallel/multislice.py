"""MPMD pipeline-over-DCN: one program per slice, explicit transfers.

The single-program GSPMD path treats a multi-slice job as one SPMD
computation over one global mesh — every cross-slice layout transition
becomes a compiler-inserted collective on the slow DCN link, and a
layout conflict becomes the "involuntary full rematerialization"
reshard (MULTICHIP_r05). This module is the other architecture
("Scaling Deep Learning Training with MPMD Pipeline Parallelism",
PAPERS.md): pipeline stages as SEPARATE programs, one per slice, each
compiled against its own per-slice mesh, with activations/gradients
moved across the DCN boundary by EXPLICIT ``jax.device_put`` transfers
the schedule controls — DCN traffic is exactly the activation tensors,
never a partitioner surprise.

Shape of the engine:

- **Stages** come from the existing block partitioning
  (parallel/pipeline.py): the stacked ``[L, ...]`` block params split
  into ``S`` contiguous chunks; stage 0 additionally owns the embedder,
  stage S-1 the head + loss. Per-stage meshes are chosen INDEPENDENTLY
  (pure data-parallel over the slice's chips by default — tensor axes
  never cross DCN by construction).
- **Programs** per stage: forward (mid stages), a fused
  forward+loss+backward for the last stage, backward-with-recompute for
  the others (activations are recomputed inside the stage's backward
  program instead of stashing VJP residuals across host boundaries —
  the standard remat trade), and a shard-local optimizer update.
- **Schedule**: microbatched 1F1B — warmup forwards, steady one-
  forward-one-backward, drain — executed as a dependency-driven
  round-robin over stages (a valid linearization on one host; on real
  multi-slice deployments each slice runs only its own column).
  Per-op wall times feed a list-schedule model that reports the
  pipeline-bubble fraction and per-stage busy time; bubble seconds
  become the ``pipeline_bubble`` badput category in the goodput ledger
  (obs/goodput.py).
- **Accounting**: every explicit cross-stage transfer is counted
  (direction, bytes), so DCN bytes/step is measured from the transfers
  the schedule actually made — comparable against the single-program
  arm's modeled HLO bytes (bench.py --mode multislice).

Gradient semantics: microbatch losses are per-microbatch means, so the
step's gradient is the microbatch-gradient mean (equal microbatch
sizes); global-norm clipping is applied across ALL stages (per-stage
squared norms summed on host — the cross-stage scalar every stage's
update consumes), so the math matches the single-program
``optax.clip_by_global_norm`` + per-leaf optimizer exactly; parity is
asserted to <=1e-5 by the bench.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any

# program kinds in the schedule (also the AOT-export key suffix)
FWD = "fwd"
BWD = "bwd"
FWDBWD = "fwdbwd"   # the last stage's fused forward+loss+backward


def slice_device_groups(devices: Sequence, num_slices: int) -> list:
    """Split the global device list into per-slice groups (DCN-major
    enumeration: slice i = the i-th contiguous chunk — the same
    convention as obs/collectives.slice_assignment)."""
    devices = list(devices)
    if num_slices < 1 or len(devices) % num_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into "
            f"{num_slices} slices")
    per = len(devices) // num_slices
    return [devices[i * per:(i + 1) * per] for i in range(num_slices)]


def stage_meshes(devices: Sequence, num_slices: int) -> list[Mesh]:
    """One pure-DP mesh per slice ("data" over the slice's chips).
    Per-stage meshes are independent by construction — a stage could
    refine to data x tensor inside its slice without touching the
    others; the DP default keeps every collective intra-slice."""
    return [Mesh(np.asarray(g), ("data",))
            for g in slice_device_groups(devices, num_slices)]


def partition_stacked(params: PyTree, num_stages: int) -> list[PyTree]:
    """Split stacked block params (leading ``layers`` dim,
    parallel/pipeline.py convention) into ``num_stages`` contiguous
    per-stage chunks."""
    leaves = jax.tree.leaves(params)
    if not leaves:
        raise ValueError("no stacked block params to partition")
    num_layers = leaves[0].shape[0]
    if num_layers % num_stages:
        raise ValueError(
            f"{num_layers} layers not divisible by {num_stages} stages")
    per = num_layers // num_stages
    return [jax.tree.map(lambda l, s=s: l[s * per:(s + 1) * per], params)
            for s in range(num_stages)]


# --------------------------------------------------------------------------
# 1F1B schedule: per-stage op order + the measured-duration timeline model


def stage_op_order(stage: int, num_stages: int,
                   num_micro: int) -> list[tuple[str, int]]:
    """The 1F1B op sequence for one stage: warmup forwards, steady
    one-backward-one-forward, drain backwards. The last stage runs the
    fused forward+backward per microbatch (zero warmup)."""
    if num_stages == 1:
        return [(FWDBWD, m) for m in range(num_micro)]
    if stage == num_stages - 1:
        return [(FWDBWD, m) for m in range(num_micro)]
    warmup = min(num_micro, num_stages - 1 - stage)
    ops: list[tuple[str, int]] = [(FWD, m) for m in range(warmup)]
    nf, nb = warmup, 0
    # steady state is forward-FIRST (fwd k+warmup, then bwd k): the
    # stage keeps S-1-stage activations in flight, so its forward for
    # the NEXT microbatch overlaps downstream stages' work — ordering
    # the backward first would serialize the whole pipeline
    while nf < num_micro:
        ops.append((FWD, nf))
        nf += 1
        ops.append((BWD, nb))
        nb += 1
    while nb < num_micro:
        ops.append((BWD, nb))
        nb += 1
    return ops


def _deps(kind: str, stage: int, micro: int,
          num_stages: int) -> list[tuple[str, int, int]]:
    """Cross-stage dependencies of one schedule op (intra-stage order is
    the stage's own op list)."""
    deps = []
    if kind in (FWD, FWDBWD) and stage > 0:
        deps.append((FWD, stage - 1, micro))
    if kind == BWD and stage < num_stages - 1:
        prev = FWDBWD if stage + 1 == num_stages - 1 else BWD
        deps.append((prev, stage + 1, micro))
    return deps


@dataclass
class ScheduleReport:
    """The modeled parallel timeline of one executed step, from measured
    per-op durations + modeled transfer latency. On a real multi-slice
    deployment every stage is its own hardware and the makespan is the
    wall clock; on the CPU emulation stages share host cores and run
    serially, so the model (not the serial wall) is the honest bubble
    number — stated wherever it is reported (PERF.md)."""

    num_stages: int
    num_microbatches: int
    makespan_s: float           # modeled parallel wall of one step
    stage_busy_s: list          # per-stage sum of op durations
    bubble_s: float             # sum over stages of (makespan - busy)
    bubble_fraction: float      # bubble_s / (num_stages * makespan)
    serial_wall_s: float        # measured host wall (CPU-serial)
    dcn_bytes: int              # explicit cross-stage transfer bytes
    dcn_transfers: int

    def to_dict(self) -> dict:
        return {
            "numStages": self.num_stages,
            "numMicrobatches": self.num_microbatches,
            "makespanS": round(self.makespan_s, 6),
            "stageBusyS": [round(b, 6) for b in self.stage_busy_s],
            "bubbleS": round(self.bubble_s, 6),
            "bubbleFraction": round(self.bubble_fraction, 6),
            "serialWallS": round(self.serial_wall_s, 6),
            "dcnBytesPerStep": int(self.dcn_bytes),
            "dcnTransfersPerStep": int(self.dcn_transfers),
            # the analytic GPipe bound for reference: (S-1)/(M+S-1)
            "idealBubbleFraction": round(
                (self.num_stages - 1) /
                (self.num_microbatches + self.num_stages - 1), 6),
        }


def model_schedule(durations: dict, num_stages: int, num_micro: int,
                   transfer_s: float = 0.0,
                   serial_wall_s: float = 0.0,
                   dcn_bytes: int = 0,
                   dcn_transfers: int = 0) -> ScheduleReport:
    """List-schedule the 1F1B grid with measured op durations:
    each stage is a serial resource executing its op order; an op starts
    at max(stage free, deps done + transfer). Returns the makespan /
    per-stage busy / bubble decomposition."""
    finish: dict = {}
    free = [0.0] * num_stages
    busy = [0.0] * num_stages
    orders = [stage_op_order(s, num_stages, num_micro)
              for s in range(num_stages)]
    cursor = [0] * num_stages
    remaining = sum(len(o) for o in orders)
    while remaining:
        progressed = False
        for s in range(num_stages):
            if cursor[s] >= len(orders[s]):
                continue
            kind, m = orders[s][cursor[s]]
            deps = _deps(kind, s, m, num_stages)
            if any(d not in finish for d in deps):
                continue
            ready = max([finish[d] + transfer_s for d in deps],
                        default=0.0)
            start = max(free[s], ready)
            dur = float(durations.get((kind, s, m), 0.0))
            finish[(kind, s, m)] = start + dur
            free[s] = start + dur
            busy[s] += dur
            cursor[s] += 1
            remaining -= 1
            progressed = True
        if not progressed:   # defensive: a dep cycle would spin forever
            raise RuntimeError("1F1B schedule deadlocked (bad deps)")
    makespan = max(free) if num_stages else 0.0
    bubble = sum(max(0.0, makespan - b) for b in busy)
    return ScheduleReport(
        num_stages=num_stages, num_microbatches=num_micro,
        makespan_s=makespan, stage_busy_s=busy, bubble_s=bubble,
        bubble_fraction=(bubble / (num_stages * makespan)
                        if makespan > 0 else 0.0),
        serial_wall_s=serial_wall_s, dcn_bytes=dcn_bytes,
        dcn_transfers=dcn_transfers)


# --------------------------------------------------------------------------
# the engine


@dataclass
class MultisliceState:
    """Per-stage training state: params/opt_state lists indexed by
    stage, each resident on its own slice's mesh."""

    step: jax.Array
    params: list
    opt_state: list


jax.tree_util.register_dataclass(
    MultisliceState,
    data_fields=["step", "params", "opt_state"],
    meta_fields=[],
)


@dataclass
class MPMDPipeline:
    """The per-slice-program train step (see module docstring).

    Stage functions (the PipelinedTransformerLM contract,
    models/transformer.py):

    - ``embed_fn(embed_params, tokens) -> h``          (stage 0 prologue)
    - ``block_fn(layer_params, h) -> h``               (one block; each
      stage scans its chunk — parallel/pipeline.py BlockFn)
    - ``head_loss_fn(head_params, h, tokens) -> (loss, aux)``
                                                        (stage S-1)

    ``optimizer`` must be a per-leaf transform (adamw, sgd, ...);
    cross-leaf global-norm clipping is the engine's own
    ``grad_clip_norm`` — applied across ALL stages' gradients, exactly
    like ``optax.clip_by_global_norm`` in the single-program chain.
    """

    meshes: list                   # one per stage (stage_meshes)
    embed_fn: Callable
    block_fn: Callable
    head_loss_fn: Callable
    optimizer: Any                 # optax.GradientTransformation
    num_microbatches: int
    grad_clip_norm: Optional[float] = None
    # modeled per-transfer DCN latency for the schedule model (the
    # emulation's device_put does not traverse a real DCN link);
    # bytes/bandwidth at the comm model's default DCN rate when None
    transfer_seconds: Optional[float] = None
    last_report: Optional[ScheduleReport] = field(default=None,
                                                  init=False)
    _programs: dict = field(default_factory=dict, init=False)
    _example_args: dict = field(default_factory=dict, init=False)

    def __post_init__(self):
        if self.num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        if not self.meshes:
            raise ValueError("need at least one stage mesh")
        # sharding-invariant RNG, same rationale as TrainStepBuilder
        jax.config.update("jax_threefry_partitionable", True)

    @property
    def num_stages(self) -> int:
        return len(self.meshes)

    # -- placement ---------------------------------------------------------

    def _batch_sharding(self, stage: int) -> NamedSharding:
        return NamedSharding(self.meshes[stage], P("data"))

    def _replicated(self, stage: int) -> NamedSharding:
        return NamedSharding(self.meshes[stage], P())

    def place_batch(self, batch: PyTree) -> PyTree:
        """HOST placement, deliberately: the schedule feeds ONE
        microbatch per tick (stage 0's data sharding) and the last
        stage its targets, each an explicit device_put — pre-placing
        the whole global batch on stage 0 would only be copied back to
        host and re-split every step. Keeping the batch as numpy makes
        the per-step split free and the per-microbatch H2D the only
        transfer."""
        return jax.tree.map(np.asarray, batch)

    # -- init --------------------------------------------------------------

    def init(self, full_init_fn: Callable[[jax.Array], PyTree],
             rng: jax.Array) -> MultisliceState:
        """Initialize from the FULL pipelined param tree
        (``{"embed", "blocks", "head"}`` — PipelinedTransformerLM.init)
        so MPMD and single-program arms share bit-identical initial
        params, then partition: stage 0 owns embed + its block chunk,
        stage S-1 its chunk + head."""
        full = full_init_fn(rng)
        chunks = partition_stacked(full["blocks"], self.num_stages)
        params = []
        for s in range(self.num_stages):
            p: dict = {"blocks": chunks[s]}
            if s == 0:
                p["embed"] = full["embed"]
            if s == self.num_stages - 1:
                p["head"] = full["head"]
            params.append(jax.device_put(p, self._replicated(s)))
        opt = [jax.device_put(self.optimizer.init(p),
                              self._replicated(s))
               for s, p in enumerate(params)]
        return MultisliceState(step=jnp.zeros((), jnp.int32),
                               params=params, opt_state=opt)

    # -- per-stage programs (jitted lazily, cached) ------------------------

    def _stage_fwd(self, params: dict, x) :
        """One stage's forward: embed (stage 0) + scan its block chunk.
        The head is NOT applied here — the last stage runs fused."""
        if "embed" in params:
            x = self.embed_fn(params["embed"], x)

        def body(h, p_layer):
            return self.block_fn(p_layer, h), None

        h, _ = jax.lax.scan(body, x, params["blocks"])
        return h

    def _program(self, kind: str, stage: int) -> Callable:
        key = (kind, stage)
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        mesh = self.meshes[stage]
        if kind == FWD:
            def run(params, x):
                return self._stage_fwd(params, x)
        elif kind == FWDBWD:
            # the last stage: forward through its blocks + head, loss,
            # and the backward in ONE program (no separate fwd op — the
            # 1F1B grid treats it as one op on this stage). A
            # single-stage pipeline's input is the integer tokens — no
            # activation cotangent exists to return.
            x_differentiable = stage > 0

            def run(params, x, tokens):
                def f(p, h):
                    h = self._stage_fwd({k: v for k, v in p.items()
                                         if k != "head"}, h)
                    loss, aux = self.head_loss_fn(p["head"], h, tokens)
                    return loss, aux
                argnums = (0, 1) if x_differentiable else (0,)
                (loss, aux), grads = jax.value_and_grad(
                    f, argnums=argnums, has_aux=True)(params, x)
                dx = grads[1] if x_differentiable else None
                return loss, aux, grads[0], dx
        elif kind == BWD:
            # backward with in-program forward recompute: dL/dparams and
            # dL/dx from the incoming output cotangent
            def run(params, x, g):
                out, vjp = jax.vjp(
                    lambda p, h: self._stage_fwd(p, h), params, x)
                dparams, dx = vjp(g)
                return dparams, dx
        else:
            raise ValueError(kind)
        with mesh:
            prog = jax.jit(run)
        self._programs[key] = prog
        return prog

    def _update_program(self, stage: int) -> Callable:
        key = ("update", stage)
        prog = self._programs.get(key)
        if prog is not None:
            return prog

        def run(params, opt_state, grad_acc, scale):
            # scale folds the microbatch average AND the cross-stage
            # global-norm clip factor (computed on host from every
            # stage's squared norm) into one elementwise multiply
            grads = jax.tree.map(lambda g: g * scale, grad_acc)
            updates, new_opt = self.optimizer.update(
                grads, opt_state, params)
            import optax
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt

        with self.meshes[stage]:
            prog = jax.jit(run)
        self._programs[key] = prog
        return prog

    def _sqnorm_program(self, stage: int) -> Callable:
        key = ("sqnorm", stage)
        prog = self._programs.get(key)
        if prog is not None:
            return prog

        def run(grads):
            return sum(jnp.sum(jnp.square(g))
                       for g in jax.tree.leaves(grads))

        with self.meshes[stage]:
            prog = jax.jit(run)
        self._programs[key] = prog
        return prog

    # -- the step ----------------------------------------------------------

    def _transfer(self, x, stage: int, record: list):
        """Explicit cross-stage transfer — THE DCN hop. Bytes counted
        per transfer; on real multi-slice hardware this is the
        host/ICI->DCN send-recv the MPMD paper schedules explicitly."""
        y = jax.device_put(x, self._batch_sharding(stage))
        record.append(int(getattr(x, "nbytes", 0)))
        return y

    def step(self, state: MultisliceState,
             batch: PyTree) -> tuple[MultisliceState, dict]:
        S = self.num_stages
        M = self.num_microbatches
        tokens = batch["tokens"]
        B = tokens.shape[0]
        if B % M:
            raise ValueError(
                f"global batch {B} not divisible by {M} microbatches")
        mb = B // M
        for s, mesh in enumerate(self.meshes):
            dp = int(mesh.shape.get("data", 1))
            if mb % dp:
                raise ValueError(
                    f"microbatch of {mb} rows (global {B} / {M} "
                    f"microbatches) not divisible by stage {s}'s "
                    f"{dp}-way data axis")
        t_wall0 = time.perf_counter()
        transfers: list[int] = []
        durations: dict = {}
        # per-microbatch buffers
        fwd_out: dict = {}      # (stage, micro) -> activation (on stage)
        cot_in: dict = {}       # (stage, micro) -> incoming cotangent
        grad_acc: list = [None] * S
        losses: list = []
        auxes: list = []

        # microbatch split on host, each placed on stage 0's mesh (the
        # schedule feeds one microbatch per tick; place_batch keeps
        # the batch host-side so this split is free — np.asarray is a
        # no-op on numpy input, a one-time D2H only if the caller fed
        # a device array directly)
        tok_host = np.asarray(tokens)
        micro_tok = [jax.device_put(tok_host[m * mb:(m + 1) * mb],
                                    self._batch_sharding(0))
                     for m in range(M)]

        def run_op(kind, s, m):
            t0 = time.perf_counter()
            if kind == FWD:
                x = micro_tok[m] if s == 0 else \
                    self._transfer(fwd_out[(s - 1, m)], s, transfers)
                out = self._program(FWD, s)(state.params[s], x)
                jax.block_until_ready(out)
                fwd_out[(s, m)] = out
            elif kind == FWDBWD:
                if S == 1:
                    x = micro_tok[m]
                    tok = micro_tok[m]
                else:
                    x = self._transfer(
                        fwd_out.pop((s - 1, m)), s, transfers)
                    tok = self._transfer(micro_tok[m], s, transfers)
                loss, aux, dparams, dx = self._program(FWDBWD, s)(
                    state.params[s], x, tok)
                jax.block_until_ready(loss)
                losses.append(loss)
                auxes.append(aux)
                _accumulate(grad_acc, s, dparams)
                if S > 1:
                    cot_in[(s - 1, m)] = dx
            else:  # BWD
                g = self._transfer(cot_in.pop((s, m)), s, transfers)
                x = micro_tok[m] if s == 0 else fwd_out[(s - 1, m)]
                if s > 0:
                    # the saved input activation already lives on the
                    # PREVIOUS stage's mesh; moving it back is part of
                    # this stage's recompute cost on the emulation (a
                    # real deployment stashes its own input locally) —
                    # placed, not counted as DCN (it never left this
                    # boundary's pair on hardware)
                    x = jax.device_put(x, self._batch_sharding(s))
                dparams, dx = self._program(BWD, s)(state.params[s], x, g)
                jax.block_until_ready(dparams)
                _accumulate(grad_acc, s, dparams)
                if s > 0:
                    cot_in[(s - 1, m)] = dx
                fwd_out.pop((s - 1, m), None)
            durations[(kind, s, m)] = time.perf_counter() - t0

        # dependency-driven round-robin over the per-stage 1F1B orders —
        # a valid linearization of the parallel schedule on one host
        orders = [stage_op_order(s, S, M) for s in range(S)]
        cursor = [0] * S
        done: set = set()
        remaining = sum(len(o) for o in orders)
        while remaining:
            progressed = False
            for s in range(S):
                if cursor[s] >= len(orders[s]):
                    continue
                kind, m = orders[s][cursor[s]]
                if any(d not in done for d in _deps(kind, s, m, S)):
                    continue
                run_op(kind, s, m)
                done.add((kind, s, m))
                cursor[s] += 1
                remaining -= 1
                progressed = True
            if not progressed:
                raise RuntimeError("1F1B execution deadlocked")

        # cross-stage global-norm clip + per-stage updates
        sq = [float(self._sqnorm_program(s)(grad_acc[s]))
              for s in range(S)]
        gnorm = float(np.sqrt(sum(sq))) / M   # norm of the averaged grad
        scale = 1.0 / M
        if self.grad_clip_norm is not None and \
                gnorm > self.grad_clip_norm:
            scale *= self.grad_clip_norm / gnorm
        new_params = []
        new_opt = []
        for s in range(S):
            p, o = self._update_program(s)(
                state.params[s], state.opt_state[s], grad_acc[s],
                jnp.float32(scale))
            new_params.append(p)
            new_opt.append(o)
        jax.block_until_ready(new_params)
        serial_wall = time.perf_counter() - t_wall0

        dcn_bytes = sum(transfers)
        xfer_s = self.transfer_seconds
        if xfer_s is None:
            from ..obs.collectives import DCN_GBPS_ENV, DEFAULT_DCN_GBPS, _bw
            per = (dcn_bytes / max(1, len(transfers))) if transfers else 0
            xfer_s = per / (_bw(DCN_GBPS_ENV, DEFAULT_DCN_GBPS) * 1e9)
        self.last_report = model_schedule(
            durations, S, M, transfer_s=xfer_s,
            serial_wall_s=serial_wall, dcn_bytes=dcn_bytes,
            dcn_transfers=len(transfers))

        loss = float(np.mean([float(l) for l in losses]))
        # pipeline_bubble_s is the WALL-clock-equivalent idle: bubble_s
        # is stage-seconds (summed over S stages), so the per-step wall
        # share is bubble_s / S = bubble_fraction x makespan — the
        # number the goodput ledger may charge against one wall clock
        # (charging raw stage-seconds would overstate badput S-fold)
        metrics = {"loss": loss, "grad_norm": gnorm,
                   "pipeline_bubble_s":
                       float(self.last_report.bubble_s / max(1, S)),
                   "bubble_fraction":
                       float(self.last_report.bubble_fraction)}
        for k in (auxes[0] if auxes else {}):
            metrics.setdefault(
                k, float(np.mean([float(a[k]) for a in auxes])))
        return MultisliceState(step=state.step + 1, params=new_params,
                               opt_state=new_opt), metrics

    __call__ = step

    # -- per-stage AOT export ----------------------------------------------

    def reset_programs(self) -> None:
        """Drop every cached/loaded stage program — the last rung of
        the AOT fallback ladder (a loaded executable that fails at its
        first dispatch recompiles fresh via the jit path)."""
        self._programs.clear()

    def stage_hlo(self, kind: str, stage: int, *abstract_args) -> str:
        """The compiled HLO of one stage program (comm-analyzer input:
        per-stage programs must carry NO cross-slice collectives — every
        DCN byte is an explicit transfer)."""
        with self.meshes[stage]:
            return self._program(kind, stage).lower(
                *abstract_args).compile().as_text()

    def export_stages(self, aot_dir: str, state: MultisliceState,
                      batch: PyTree,
                      key_fn: Callable[[int, str], str]) -> list[str]:
        """AOT-export every stage program (runtime/aot.py): the caller's
        ``key_fn(stage, program_kind)`` builds each key — aot.step_key
        already carries topology x numSlices, so the stage index + kind
        ride its ``extra`` and an N-program job warms N executables:
        cold start stays flat in N (ISSUE 15 tentpole). Returns the
        written keys; failures degrade per-program (aot.export_step
        contract)."""
        from ..runtime import aot as aot_mod
        written = []
        for s, kind, args in self._abstract_stage_args(state, batch):
            cached = self._programs.get((kind, s))
            if cached is not None and not hasattr(cached, "lower"):
                # already an AOT-loaded executable (load_stages seeded
                # it) — it came FROM this volume, so a partial warm
                # start only exports the programs that are missing
                continue
            with self.meshes[s]:
                compiled = self._program(kind, s).lower(*args).compile()
            key = key_fn(s, kind)
            sig = aot_mod.abstract_signature(*args)
            aot_mod.export_step(aot_dir, key, compiled, sig)
            written.append(key)
        return written

    @property
    def num_programs(self) -> int:
        """Schedule-facing programs: FWD + BWD per non-last stage, one
        fused FWDBWD on the last — 2S-1 (1 when S == 1)."""
        return max(1, 2 * self.num_stages - 1)

    def _abstract_stage_args(self, state: MultisliceState,
                             batch: PyTree):
        """(stage, program kind, abstract example args) for every
        schedule-facing program — each arg carries the SHARDING the
        schedule actually feeds (the stage's batch sharding), so an
        exported executable's layout matches the runtime call
        exactly."""
        tokens = batch["tokens"]
        mb = tokens.shape[0] // self.num_microbatches
        h = None
        for s in range(self.num_stages):
            last = s == self.num_stages - 1
            tok_s = jax.ShapeDtypeStruct(
                (mb,) + tokens.shape[1:], tokens.dtype,
                sharding=self._batch_sharding(s))
            if last:
                x_in = tok_s if s == 0 else h
                yield s, FWDBWD, (state.params[s], x_in, tok_s)
                continue
            x_in = tok_s if s == 0 else h
            yield s, FWD, (state.params[s], x_in)
            # abstract next-stage input from the PURE stage fn (a
            # loaded Compiled cannot be traced by eval_shape); the
            # stage's own output cotangent has the same shape, on ITS
            # mesh — the backward program's third arg
            out = jax.eval_shape(self._stage_fwd, state.params[s], x_in)
            g_s = jax.ShapeDtypeStruct(
                out.shape, out.dtype, sharding=self._batch_sharding(s))
            yield s, BWD, (state.params[s], x_in, g_s)
            h = jax.ShapeDtypeStruct(
                out.shape, out.dtype,
                sharding=self._batch_sharding(s + 1))

    def load_stages(self, aot_dir: str, state: MultisliceState,
                    batch: PyTree,
                    key_fn: Callable[[int, str], str]) -> int:
        """Seed the per-stage program cache from AOT-exported
        executables (the warm-start rung): each loaded
        ``jax.stages.Compiled`` stands in for the jitted program — no
        trace, no lower, no XLA for that stage. Every failure falls back
        to the jit path for THAT stage only (the aot.load_step ladder
        contract). Returns how many stage programs loaded."""
        from ..runtime import aot as aot_mod
        loaded = 0
        for s, kind, args in self._abstract_stage_args(state, batch):
            prog = aot_mod.load_step(aot_dir, key_fn(s, kind),
                                     aot_mod.abstract_signature(*args))
            if prog is not None:
                self._programs[(kind, s)] = prog
                loaded += 1
        return loaded


def _accumulate(acc: list, stage: int, grads: PyTree) -> None:
    if acc[stage] is None:
        acc[stage] = grads
    else:
        acc[stage] = jax.tree.map(jnp.add, acc[stage], grads)
