"""jax API compatibility: one shard_map entry point for every call site.

The codebase is written against the modern ``jax.shard_map`` surface
(``axis_names`` selects the manually-mapped axes, ``check_vma`` toggles
the replication checker). Older jax releases ship the same machinery as
``jax.experimental.shard_map.shard_map`` with the inverse vocabulary
(``auto`` = the axes NOT manually mapped, ``check_rep``). This module
translates so kernels and schedules run unchanged on both.
"""

from __future__ import annotations

from typing import Optional

import jax

_FORCE_LEGACY = False   # tests flip this to exercise the legacy branch


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` on modern jax; on older releases psum of the
    unit constant, which folds to the static mapped-axis size."""
    if hasattr(jax.lax, "axis_size") and not _FORCE_LEGACY:
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs,
              axis_names: Optional[set] = None, check_vma: bool = True):
    """``jax.shard_map`` with the modern signature on any jax.

    axis_names: the mesh axes the body is manual over (None = all of
    them); check_vma: run jax's replication/VMA checker (False for bodies
    whose collectives the checker cannot type, e.g. psum of a
    conditionally-zeroed tensor).
    """
    if hasattr(jax, "shard_map") and not _FORCE_LEGACY:
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    # Legacy jax: partial-manual lowering (auto != {}) check-fails inside
    # XLA's sharding utils on some backends (IsManualSubgroup), so go full
    # manual instead: axes absent from the specs are replicated, which
    # preserves numerics exactly — the body's collectives only ever name
    # its manual axes — at the cost of redundant compute over the auto
    # axes. Only legacy jax pays this; modern jax gets true partial-manual.
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
