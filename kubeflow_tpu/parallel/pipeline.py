"""Pipeline parallelism: GPipe-style microbatched stage execution.

The reference has no pipeline parallelism anywhere (SURVEY.md §2.5 row 5 —
platform repo, parallelism delegated to launched frameworks); the TPU build
supplies it natively as one of the sharding-spec axes of the TPUJob. This
module is the execution engine behind ``ShardingSpec.pipeline > 1``.

Design (TPU-first):
- Layers are *stacked*: every block parameter carries a leading ``layers``
  dim, sharded over the ``pipeline`` mesh axis — contiguous groups of
  layers land on each stage, so stage weights live entirely in that
  stage's HBM (the point of PP: fit models deeper than one chip's HBM).
- Execution runs under a **partial-manual shard_map over only the
  "pipeline" axis**: data/fsdp/tensor axes stay under automatic GSPMD, so
  PP composes with DP/FSDP/TP without manual collectives for those axes.
- The schedule is GPipe: the global batch splits into M microbatches; at
  tick t, stage s processes microbatch (t-s) and hands its activation to
  stage s+1 via ``lax.ppermute`` (a point-to-point ICI hop between
  neighboring stages — the cheapest collective on a TPU torus). The
  bubble is the standard (S-1)/(M+S-1) fraction; callers pick M >= 4*S.
- The whole schedule is a ``lax.scan`` over ticks: one traced tick body,
  XLA-friendly static control flow (SURVEY.md: no data-dependent Python
  control flow under jit).

Grad flow: ppermute transposes to the inverse permutation, the scan
transposes to a reverse-time scan — reverse-order pipelining of the
backward pass falls out of autodiff, no hand-written backward schedule.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

PyTree = Any
# block_fn(per_layer_params, activations) -> activations (same shape)
BlockFn = Callable[[PyTree, jax.Array], jax.Array]

PIPELINE_AXIS = "pipeline"


def stage_sharding_spec(ndim: int, axis: str = PIPELINE_AXIS) -> P:
    """PartitionSpec for a stacked-layer param leaf: leading dim over the
    pipeline axis, the rest replicated (tensor axes may refine under auto
    GSPMD outside the manual axis)."""
    return P(axis, *([None] * (ndim - 1)))


def num_ticks(num_microbatches: int, num_stages: int) -> int:
    return num_microbatches + num_stages - 1


def pipeline_apply(block_fn: BlockFn,
                   stacked_params: PyTree,
                   x: jax.Array,
                   *,
                   mesh: Mesh,
                   num_microbatches: int,
                   axis: str = PIPELINE_AXIS) -> jax.Array:
    """Apply ``num_layers`` stacked blocks to ``x`` through a pipeline.

    Args:
      block_fn: applies ONE block: ``(layer_params, h) -> h`` (same shape).
      stacked_params: pytree whose leaves have leading dim ``num_layers``
        (must divide by the pipeline axis size), sharded with
        :func:`stage_sharding_spec`.
      x: activations ``[batch, ...]``; batch must divide by
        ``num_microbatches`` (and the microbatch by the data axes).
      mesh: the device mesh (must contain ``axis``).
      num_microbatches: GPipe M. M == 1 degenerates to sequential stages
        (still correct, maximal bubble).

    Returns activations of the same shape, replicated over the pipeline
    axis (so the head/loss downstream is pipeline-agnostic).
    """
    n_stages = mesh.shape.get(axis, 1)
    if n_stages <= 1:
        # No pipeline axis: plain scan over stacked layers.
        def body(h, p_layer):
            return block_fn(p_layer, h), None
        out, _ = jax.lax.scan(body, x, stacked_params)
        return out

    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by microbatches {num_microbatches}")
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % n_stages:
        raise ValueError(
            f"{num_layers} layers not divisible by {n_stages} stages")

    mb = batch // num_microbatches
    x_mb = x.reshape((num_microbatches, mb) + x.shape[1:])

    fwd = _pipeline_shardmap(block_fn, mesh, axis, n_stages,
                             num_microbatches)
    out_mb = fwd(stacked_params, x_mb)
    return out_mb.reshape(x.shape)


def _pipeline_shardmap(block_fn: BlockFn, mesh: Mesh, axis: str,
                       n_stages: int, n_micro: int):
    """The partial-manual shard_map GPipe schedule over the pipeline axis."""
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    n_tick = num_ticks(n_micro, n_stages)

    def stage_apply(p_local, h):
        # p_local leaves: [layers_per_stage, ...] — scan the local layers.
        def body(h, p_layer):
            return block_fn(p_layer, h), None
        h, _ = jax.lax.scan(body, h, p_local)
        return h

    def pp_body(p_local, x_mb, dtype):
        # x_mb crosses the shard_map boundary in f32: it is replicated over
        # the pipeline axis, so its transpose is a psum, and bf16 psum under
        # a partial-manual shard_map crashes XLA's SPMD partitioner on some
        # backends. Compute still runs in the caller's dtype.
        x_mb = x_mb.astype(dtype)
        stage = jax.lax.axis_index(axis)
        is_first = stage == 0
        is_last = stage == n_stages - 1

        def tick(carry, t):
            state, out = carry
            # Stage 0 ingests microbatch t (clipped; invalid ticks feed a
            # dummy that never reaches the output window).
            feed = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            h_in = jnp.where(is_first, feed, state)
            h_out = stage_apply(p_local, h_in)
            # Last stage finished microbatch t-(S-1) this tick.
            mb_idx = t - (n_stages - 1)
            slot = jnp.clip(mb_idx, 0, n_micro - 1)
            valid = is_last & (mb_idx >= 0)
            prev = jax.lax.dynamic_index_in_dim(out, slot, 0, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, h_out, prev), slot, 0)
            state = jax.lax.ppermute(h_out, axis, ring)
            return (state, out), None

        init = (jnp.zeros_like(x_mb[0]), jnp.zeros_like(x_mb))
        (_, out), _ = jax.lax.scan(tick, init, jnp.arange(n_tick))
        # Replicate the last stage's outputs across the pipeline axis so the
        # downstream head/loss sees identical values on every stage. The
        # psum rides in f32: XLA's partial-manual partitioner rejects bf16
        # psum on some backends, and f32 matches grad-reduction precision.
        out_sel = jnp.where(is_last, out, jnp.zeros_like(out))
        # f32 out through the boundary too (cast back in run()).
        return jax.lax.psum(out_sel.astype(jnp.float32), axis)

    def specs_for(params):
        return jax.tree.map(lambda l: stage_sharding_spec(l.ndim, axis),
                            params)

    def run(stacked_params, x_mb):
        in_specs = (specs_for(stacked_params), P())
        dtype = x_mb.dtype
        body = lambda p, x: pp_body(p, x, dtype)  # noqa: E731
        from .compat import shard_map
        out = shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=P(),
            axis_names={axis}, check_vma=False)(
                stacked_params, x_mb.astype(jnp.float32))
        return out.astype(dtype)

    return run
