"""KfDef — the platform deployment config (the app.yaml state file).

Reference: bootstrap/pkg/apis/apps/kfdef/v1alpha1/application_types.go
(KfDefSpec :24-41, AppConfig :124-131, KfDef :159-165, conditions :142-157)
and the layered config system described in SURVEY.md §5: CLI flags → options →
KfDef persisted as app.yaml → per-platform shipped defaults → per-component
params.

The TPU build keeps the same surface: a typed spec with platform, component
list, per-component params, and status conditions; `kfctl` persists it to the
app directory and every verb re-loads it (coordinator.LoadKfApp analog).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ..utils import yamlio

KFDEF_API_VERSION = "kfdef.tpu.kubeflow.org/v1alpha1"
KFDEF_KIND = "KfDef"
APP_FILE = "app.yaml"

# Platforms, mirroring group.go:134-138 (gcp, minikube, dockerfordesktop) plus
# the "existing cluster" driver that is this build's primary local path.
PLATFORM_GCP = "gcp"
PLATFORM_MINIKUBE = "minikube"
PLATFORM_DOCKER_FOR_DESKTOP = "dockerfordesktop"
PLATFORM_EXISTING = "existing"
PLATFORM_NONE = "none"
ALL_PLATFORMS = (PLATFORM_GCP, PLATFORM_MINIKUBE, PLATFORM_DOCKER_FOR_DESKTOP,
                 PLATFORM_EXISTING, PLATFORM_NONE)

# Resource enum, group.go:63-69.
RESOURCE_ALL = "all"
RESOURCE_K8S = "k8s"
RESOURCE_PLATFORM = "platform"

# Default component set: the TPU-platform analog of bootstrap/config/default.yaml:4-23.
DEFAULT_COMPONENTS = [
    "metacontroller",
    "application",
    "istio",
    "tpu-job-operator",
    "tf-job-operator",
    "pytorch-operator",
    "mpi-operator",
    "jupyter-web-app",
    "notebook-controller",
    "profiles",
    "admission-webhook",
    "centraldashboard",
    "katib",
    "kubebench",
    "argo",
    "pipeline-scheduledworkflow",
    "pipeline-db",
    "pipeline-apiserver",
    "pipeline-ui",
    "tpu-serving",
    "metric-collector",
    "spartakus",
]


@dataclass
class Condition:
    type: str
    status: str
    reason: str = ""
    message: str = ""
    last_update_time: float = field(default_factory=time.time)


@dataclass
class KfDefSpec:
    app_dir: str = ""
    platform: str = PLATFORM_EXISTING
    project: str = ""                      # cloud project (gcp)
    zone: str = ""
    namespace: str = "kubeflow"
    use_basic_auth: bool = False
    use_istio: bool = True
    components: list[str] = field(default_factory=lambda: list(DEFAULT_COMPONENTS))
    component_params: dict[str, dict[str, Any]] = field(default_factory=dict)
    # named config overlay merged over components/params at generate time
    # (the kustomize-v2 base+overlay analog, manifests/overlays.py)
    flavor: str = ""
    # on-disk config layout (base/ + overlays/<name>/config.yaml — the
    # kustomize-v2 repo-walk analog); when set, the base supplies the
    # component list and spec.flavor resolves against its overlays
    config_dir: str = ""
    # TPU-specific platform defaults applied to every training component
    default_tpu_topology: str = "v5e-8"
    version: str = "0.1.0"
    repo: str = ""                         # manifest repo override (builtin if empty)
    delete_storage: bool = False
    # path to a kubeconfig: when set, apply/delete target that real
    # apiserver (HttpKubeClient) instead of the persisted simulated cluster
    kubeconfig: str = ""

    def params_for(self, component: str) -> dict[str, Any]:
        return dict(self.component_params.get(component, {}))


@dataclass
class KfDef:
    name: str
    spec: KfDefSpec = field(default_factory=KfDefSpec)
    conditions: list[Condition] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)

    # -- (de)serialization --------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "apiVersion": KFDEF_API_VERSION,
            "kind": KFDEF_KIND,
            "metadata": {"name": self.name, "labels": self.labels,
                         "namespace": self.spec.namespace},
            "spec": {
                "appDir": self.spec.app_dir,
                "platform": self.spec.platform,
                "project": self.spec.project,
                "zone": self.spec.zone,
                "namespace": self.spec.namespace,
                "useBasicAuth": self.spec.use_basic_auth,
                "useIstio": self.spec.use_istio,
                "components": list(self.spec.components),
                "componentParams": self.spec.component_params,
                "flavor": self.spec.flavor,
                "configDir": self.spec.config_dir,
                "defaultTpuTopology": self.spec.default_tpu_topology,
                "version": self.spec.version,
                "repo": self.spec.repo,
                "deleteStorage": self.spec.delete_storage,
                "kubeconfig": self.spec.kubeconfig,
            },
            "status": {
                "conditions": [
                    {"type": c.type, "status": c.status, "reason": c.reason,
                     "message": c.message, "lastUpdateTime": c.last_update_time}
                    for c in self.conditions
                ]
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "KfDef":
        spec = d.get("spec", {}) or {}
        kf = cls(
            name=d.get("metadata", {}).get("name", "kubeflow"),
            labels=d.get("metadata", {}).get("labels", {}) or {},
            spec=KfDefSpec(
                app_dir=spec.get("appDir", ""),
                platform=spec.get("platform", PLATFORM_EXISTING),
                project=spec.get("project", ""),
                zone=spec.get("zone", ""),
                namespace=spec.get("namespace", "kubeflow"),
                use_basic_auth=bool(spec.get("useBasicAuth", False)),
                use_istio=bool(spec.get("useIstio", True)),
                # absent → defaults; an EXPLICIT empty list persists (the
                # --config-dir convention: the on-disk base supplies the
                # list, so `or DEFAULT_COMPONENTS` would resurrect all
                # ~23 defaults on every reload)
                components=(list(spec["components"])
                            if spec.get("components") is not None
                            else list(DEFAULT_COMPONENTS)),
                component_params=spec.get("componentParams", {}) or {},
                flavor=spec.get("flavor", "") or "",
                config_dir=spec.get("configDir", "") or "",
                default_tpu_topology=spec.get("defaultTpuTopology", "v5e-8"),
                version=spec.get("version", "0.1.0"),
                repo=spec.get("repo", ""),
                delete_storage=bool(spec.get("deleteStorage", False)),
                kubeconfig=spec.get("kubeconfig", ""),
            ),
        )
        for c in d.get("status", {}).get("conditions", []) or []:
            kf.conditions.append(Condition(
                type=c.get("type", ""), status=c.get("status", ""),
                reason=c.get("reason", ""), message=c.get("message", ""),
                last_update_time=c.get("lastUpdateTime", time.time()),
            ))
        return kf

    # -- app.yaml persistence (writeConfigFile / LoadKfApp analog) ----------

    def save(self, app_dir: Optional[str] = None) -> str:
        app_dir = app_dir or self.spec.app_dir
        if not app_dir:
            raise ValueError("KfDef.save: no app_dir set")
        self.spec.app_dir = app_dir  # persist the dir actually written to
        os.makedirs(app_dir, exist_ok=True)
        path = os.path.join(app_dir, APP_FILE)
        yamlio.dump_file(self.to_dict(), path)
        return path

    @classmethod
    def load(cls, app_dir: str) -> "KfDef":
        path = os.path.join(app_dir, APP_FILE)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path} not found — run `kfctl init` first (LoadKfApp analog)"
            )
        kf = cls.from_dict(yamlio.load_file(path))
        kf.spec.app_dir = app_dir
        return kf

    def set_condition(self, ctype: str, status: str, reason: str = "",
                      message: str = "") -> None:
        for c in self.conditions:
            if c.type == ctype:
                c.status, c.reason, c.message = status, reason, message
                c.last_update_time = time.time()
                return
        self.conditions.append(Condition(ctype, status, reason, message))

    def validate(self) -> None:
        if self.spec.platform not in ALL_PLATFORMS:
            raise ValueError(
                f"unknown platform {self.spec.platform!r}; valid: {ALL_PLATFORMS}"
            )
        if self.spec.platform == PLATFORM_GCP and not self.spec.project:
            raise ValueError("gcp platform requires --project")
        from .topology import parse_topology
        parse_topology(self.spec.default_tpu_topology)
